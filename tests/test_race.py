"""Battletest / race suite (reference: Go test -race across pkg/, plus
karpenter-core's randomized "battletest" helpers).

Two attack surfaces:

1. **Thread-safety stress** on the components that are contractually
   concurrent — TTLCache, UnavailableOfferings, the request Batcher, the
   metrics Registry, and the fake cloud's message queue (the interruption
   worker pool drains it in parallel).  Threads hammer mixed operations;
   the assertions are freedom-from-exception plus invariants (monotonic
   seqnums, conservation of batched requests, exact counter totals).

2. **Randomized controller-order fuzz**: the reconcile loop runs its
   controllers in a SHUFFLED order for many ticks while the workload
   churns (pods added/deleted, instances killed out-of-band, interruption
   messages injected), seeded for reproducibility.  Logical races between
   controllers (provision vs GC vs disruption vs termination) must
   converge: after settling, the consistency checker reports no
   violations and cloud state matches kube state.
"""

import random
import threading

import pytest

from karpenter_tpu.api import Pod, Resources, Settings
from karpenter_tpu.api import labels as L
from karpenter_tpu.cache.ttl import TTLCache
from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
from karpenter_tpu.batcher.core import Batcher
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.testing import Environment
from karpenter_tpu.utils.clock import FakeClock

N_THREADS = 8
OPS_PER_THREAD = 400


def _hammer(n_threads, fn):
    """Run fn(thread_idx) on n threads; re-raise the first error."""
    errors = []

    def run(i):
        try:
            fn(i)
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]


class TestThreadSafety:
    def test_ttl_cache_mixed_ops(self):
        clock = FakeClock()
        cache = TTLCache(clock, ttl=5.0)

        def attack(i):
            rng = random.Random(i)
            for n in range(OPS_PER_THREAD):
                key = f"k-{rng.randrange(32)}"
                op = rng.randrange(6)
                if op == 0:
                    cache.set(key, (i, n))
                elif op == 1:
                    v = cache.get(key)
                    assert v is None or isinstance(v, tuple)
                elif op == 2:
                    cache.touch(key)
                elif op == 3:
                    cache.delete(key)
                elif op == 4:
                    cache.purge_expired()
                else:
                    len(cache)

        _hammer(N_THREADS, attack)
        # cache still behaves after the storm
        cache.set("sanity", 1)
        assert cache.get("sanity") == 1

    def test_unavailable_offerings_seqnum_monotonic(self):
        clock = FakeClock()
        ice = UnavailableOfferings(clock)
        seen = []

        def attack(i):
            rng = random.Random(100 + i)
            for _ in range(OPS_PER_THREAD):
                t = f"type-{rng.randrange(8)}"
                z = f"zone-{rng.randrange(3)}"
                if rng.random() < 0.5:
                    ice.mark_unavailable(L.CAPACITY_TYPE_SPOT, t, z, reason="x")
                else:
                    ice.is_unavailable(L.CAPACITY_TYPE_SPOT, t, z)
                seen.append(ice.seq_num)

        _hammer(N_THREADS, attack)
        # every mark bumped the seqnum; no lost final state
        assert ice.seq_num >= max(seen)
        # lookups still behave after the storm
        ice.mark_unavailable(L.CAPACITY_TYPE_SPOT, "type-post", "zone-a")
        assert ice.is_unavailable(L.CAPACITY_TYPE_SPOT, "type-post", "zone-a")
        assert not ice.is_unavailable(L.CAPACITY_TYPE_SPOT, "never", "zone-a")

    def test_batcher_conserves_requests(self):
        """Concurrent callers through a merging batcher: every request is
        answered exactly once and batch sizes sum to the request count
        (reference createfleet.go:42-60 merge semantics)."""
        executed = []
        lock = threading.Lock()

        def executor(requests):
            with lock:
                executed.append(len(requests))
            return [r * 2 for r in requests]

        b = Batcher(
            executor=executor, idle_s=0.005, max_s=0.05, max_items=64,
            name="race-test",
        )
        results = {}

        def attack(i):
            for n in range(50):
                val = i * 1000 + n
                out = b.call(val)
                with lock:
                    results[val] = out

        _hammer(N_THREADS, attack)
        assert len(results) == N_THREADS * 50
        assert all(v == k * 2 for k, v in results.items())
        assert sum(executed) == N_THREADS * 50

    def test_registry_counts_exactly(self):
        reg = Registry()

        def attack(i):
            for _ in range(OPS_PER_THREAD):
                reg.inc("race_total", {"thread": "all"})
                reg.observe("race_seconds", 0.001)

        _hammer(N_THREADS, attack)
        assert reg.counter("race_total", {"thread": "all"}) == (
            N_THREADS * OPS_PER_THREAD
        )

    def test_fake_cloud_api_storm(self):
        """Hammer every FakeCloud API surface the batcher / launch pool /
        interruption workers hit from threads, WHILE other threads mutate
        the shared catalog/topology dicts — iteration during mutation must
        never raise (the lock-audit invariant: every API takes self._lock)."""
        from karpenter_tpu.cloud.fake.backend import (
            FakeImage,
            FakeLaunchTemplate,
            FakeSecurityGroup,
            FakeSubnet,
            generate_catalog,
        )
        from karpenter_tpu.api.objects import SelectorTerm
        from karpenter_tpu.cloud.fake.backend import FakeCloud

        cloud = FakeCloud(
            FakeClock(), shapes=generate_catalog()[:20]
        ).with_default_topology()
        term = [SelectorTerm.of(Name="*")]

        def attack(i):
            rng = random.Random(300 + i)
            for n in range(OPS_PER_THREAD // 4):
                op = rng.randrange(12)
                if op == 0:
                    cloud.create_launch_template(
                        FakeLaunchTemplate(name=f"lt-{i}-{n % 8}")
                    )
                elif op == 1:
                    cloud.describe_launch_templates()
                elif op == 2:
                    cloud.delete_launch_template(f"lt-{rng.randrange(8)}-{n % 8}")
                elif op == 3:
                    cloud.ensure_instance_profile(f"p-{rng.randrange(8)}", "role")
                elif op == 4:
                    cloud.delete_instance_profile(f"p-{rng.randrange(8)}")
                elif op == 5:
                    cloud.add_image(
                        FakeImage(id=f"im-{i}-{n % 8}", family="standard")
                    )
                elif op == 6:
                    cloud.describe_images(term)
                    cloud.latest_image("standard", "amd64")
                elif op == 7:
                    cloud.add_subnet(
                        FakeSubnet(id=f"sn-{i}-{n % 8}", zone="zone-a")
                    )
                    cloud.describe_subnets(term)
                elif op == 8:
                    cloud.add_security_group(
                        FakeSecurityGroup(id=f"sg-{i}-{n % 8}")
                    )
                    cloud.describe_security_groups(term)
                elif op == 9:
                    insts, _ = cloud.create_fleet(
                        overrides=[{
                            "instance_type": cloud.describe_instance_types()[0].name,
                            "zone": "zone-a",
                            "subnet_id": "subnet-0",
                        }],
                        capacity_type="on-demand",
                    )
                    if insts and rng.random() < 0.5:
                        cloud.terminate_instances([insts[0].id])
                elif op == 10:
                    cloud.describe_instances()
                    cloud.describe_instance_type_offerings()
                else:
                    cloud.get_products()
                    cloud.describe_spot_price_history()
                    cloud.set_capacity(
                        f"t-{rng.randrange(4)}", "zone-a", "spot", 5
                    )
                    cloud.mark_insufficient(
                        f"t-{rng.randrange(4)}", "zone-a", "spot"
                    )

        _hammer(N_THREADS, attack)
        # the cloud still behaves after the storm
        assert cloud.describe_instance_types()
        assert cloud.recorder.count("CreateFleet") > 0

    def test_queue_drained_nothing_lost(self):
        """Parallel consumers over the fake SQS: the visibility timeout
        hides in-flight messages from other consumers, and no message may
        ever be LOST (the interruption pool's floor contract)."""
        env = Environment()
        for i in range(200):
            env.cloud.send_message({"kind": "mystery", "n": i})
        consumed = []
        lock = threading.Lock()

        def attack(i):
            while True:
                msgs = env.cloud.receive_messages(max_messages=10)
                if not msgs:
                    return
                for m in msgs:
                    env.cloud.delete_message(m)
                with lock:
                    consumed.extend(m.body["n"] for m in msgs)

        _hammer(4, attack)
        assert not env.cloud.queue
        # visibility timeout makes concurrent consumption exactly-once
        # here (no consumer holds a message past the window in-test)
        assert sorted(consumed) == list(range(200))


class TestRandomizedOrderFuzz:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_shuffled_controllers_converge(self, seed):
        rng = random.Random(seed)
        env = Environment(
            settings=Settings(cluster_name="test", interruption_queue_name="q")
        )
        env.default_node_class()
        env.default_node_pool()
        op = env.operator
        controllers = [
            ("nodeclass", op.node_class_controller),
            ("provisioner", op.provisioner),
            ("lifecycle", op.lifecycle),
            ("interruption", op.interruption),
            ("disruption", op.disruption),
            ("termination", op.termination),
            ("link", op.link),
            ("garbagecollection", op.garbage_collection),
            ("tagging", op.tagging),
        ]
        live_pods = []
        for tick in range(60):
            # random workload churn
            ev = rng.random()
            if ev < 0.35:
                p = Pod(requests=Resources(cpu=rng.choice([0.5, 1, 2]),
                                           memory="1Gi"))
                env.kube.put_pod(p)
                live_pods.append(p)
            elif ev < 0.45 and live_pods:
                env.kube.delete_pod(live_pods.pop().key())
            elif ev < 0.50:
                running = [i for i in env.cloud.instances.values()
                           if i.state == "running"]
                if running:  # out-of-band kill
                    env.cloud.terminate_instances([rng.choice(running).id])
            elif ev < 0.55:
                claims = list(env.kube.node_claims.values())
                if claims:  # interruption event
                    env.cloud.send_message({
                        "kind": "rebalance_recommendation",
                        "instance_id": rng.choice(claims).provider_id,
                    })
            # one shuffled tick
            env.clock.step(rng.choice([0.5, 1.0, 2.0, 35.0]))
            env.kubelet.step()
            order = list(controllers)
            rng.shuffle(order)
            for _name, c in order:
                c.reconcile()
            env.kubelet.step()
        # convergence: settle with the canonical loop
        env.settle(max_rounds=40)
        for _ in range(3):
            env.step(35.0)  # GC grace, liveness, reaps
        env.settle(max_rounds=20)
        assert not env.kube.pending_pods()
        # kube<->cloud agreement: every live claim is backed by a running
        # instance and vice versa (modulo the GC grace window)
        live_claims = {
            c.provider_id
            for c in env.kube.node_claims.values()
            if c.deleted_at is None and c.provider_id
        }
        running = {
            i.id for i in env.cloud.instances.values() if i.state == "running"
        }
        assert live_claims <= running
        # consistency checker is the invariant oracle: zero violations
        from karpenter_tpu.controllers.consistency import CHECK_PERIOD

        env.clock.step(CHECK_PERIOD + 1)
        op.consistency.reconcile()
        violations = [
            e for e in env.kube.events if e[1] == "ConsistencyViolation"
        ]
        assert not violations, violations
