"""Lint/type gate (round-2 VERDICT hygiene item): no external linter is
baked into the image, so this enforces the two checks that catch real rot:

1. every module under karpenter_tpu/ imports cleanly, and
2. `typing.get_type_hints` resolves on every public function/method —
   which fails on annotations referencing names that were never imported
   (the `Optional`-without-import bug class).
"""

import importlib
import inspect
import pkgutil
import typing

import karpenter_tpu


def _modules():
    for info in pkgutil.walk_packages(
        karpenter_tpu.__path__, prefix="karpenter_tpu."
    ):
        yield info.name


def test_all_modules_import():
    for name in _modules():
        importlib.import_module(name)


def test_annotations_resolve():
    failures = []
    for name in _modules():
        mod = importlib.import_module(name)
        targets = []
        for _, obj in vars(mod).items():
            if inspect.isfunction(obj) and obj.__module__ == name:
                targets.append(obj)
            elif inspect.isclass(obj) and obj.__module__ == name:
                targets.append(obj)
                for _, m in vars(obj).items():
                    if inspect.isfunction(m):
                        targets.append(m)
        for t in targets:
            try:
                typing.get_type_hints(t)
            except NameError as exc:
                failures.append(f"{name}.{getattr(t, '__qualname__', t)}: {exc}")
            except Exception:
                pass  # forward refs to runtime-only types are fine
    assert not failures, "\n".join(failures)
