"""Lint/type gate (round-2 VERDICT hygiene item): no external linter is
baked into the image, so this enforces the checks that catch real rot:

1. every module under karpenter_tpu/ imports cleanly,
2. `typing.get_type_hints` resolves on every public function/method —
   which fails on annotations referencing names that were never imported
   (the `Optional`-without-import bug class), and
3. no direct `time.time()` / `time.sleep()` outside utils/clock.py — the
   simulator's determinism contract: all time flows through the
   injectable Clock, so a FakeClock compresses every wait and two equal
   seeds replay byte-identically (docs/designs/simulation.md).
"""

import importlib
import inspect
import pathlib
import pkgutil
import re
import typing

import karpenter_tpu


def _modules():
    for info in pkgutil.walk_packages(
        karpenter_tpu.__path__, prefix="karpenter_tpu."
    ):
        yield info.name


def test_all_modules_import():
    for name in _modules():
        importlib.import_module(name)


def test_annotations_resolve():
    failures = []
    for name in _modules():
        mod = importlib.import_module(name)
        targets = []
        for _, obj in vars(mod).items():
            if inspect.isfunction(obj) and obj.__module__ == name:
                targets.append(obj)
            elif inspect.isclass(obj) and obj.__module__ == name:
                targets.append(obj)
                for _, m in vars(obj).items():
                    if inspect.isfunction(m):
                        targets.append(m)
        for t in targets:
            try:
                typing.get_type_hints(t)
            except NameError as exc:
                failures.append(f"{name}.{getattr(t, '__qualname__', t)}: {exc}")
            except Exception:
                pass  # forward refs to runtime-only types are fine
    assert not failures, "\n".join(failures)


# the genuinely-wall-clock spots: the Clock abstraction itself is the one
# place allowed to read the wall.  (time.monotonic/perf_counter remain
# free — they measure host-side durations like batcher windows and solver
# phases, which no simulated clock can compress.)
_WALL_CLOCK_ALLOWLIST = {
    "karpenter_tpu/utils/clock.py",
}

_WALL_CLOCK_RE = re.compile(r"\btime\.(?:time|sleep)\s*\(")


def test_no_wall_clock_outside_clock_module():
    """Determinism contract: `time.time()`/`time.sleep()` only inside
    utils/clock.py (or the explicit allowlist).  Everything else takes an
    injected Clock, so the cluster simulator can run the whole stack on a
    FakeClock and replay a seed byte-identically."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    offenders = []
    for path in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(pkg_root.parent).as_posix()
        if rel in _WALL_CLOCK_ALLOWLIST:
            continue
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            code = line.split("#", 1)[0]
            if _WALL_CLOCK_RE.search(code):
                offenders.append(f"{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "wall-clock calls outside utils/clock.py (route through the "
        "injected Clock, or allowlist a genuinely-wall-clock spot):\n"
        + "\n".join(offenders)
    )
