"""Lint/type gate (round-2 VERDICT hygiene item): no external linter is
baked into the image, so this enforces the checks that catch real rot:

1. every module under karpenter_tpu/ imports cleanly,
2. `typing.get_type_hints` resolves on every public function/method —
   which fails on annotations referencing names that were never imported
   (the `Optional`-without-import bug class), and
3. no direct `time.time()` / `time.sleep()` outside utils/clock.py — the
   simulator's determinism contract: all time flows through the
   injectable Clock, so a FakeClock compresses every wait and two equal
   seeds replay byte-identically (docs/designs/simulation.md).
4. no `scheduler.update(...)` inside loops in controllers/ — the
   serial-simulation antipattern batched consolidation removed (each
   per-candidate update() busts the solver's compile cache and forces a
   full host compile per subset); the sanctioned call sites are
   allowlisted by qualified name.
5. every metric-name literal passed to a registry verb (.inc/.set/
   .observe/.time/...) appears in docs/metrics.md — the doc-rot guard
   with teeth: a new series cannot ship without regenerating the
   reference page (and with it the /metrics HELP/TYPE catalog).
6. every ledger event-type literal emitted via `Registry.event(...)` /
   `EventLedger.emit(...)` appears in docs/designs/observability.md —
   the same teeth for the decision-event taxonomy: SLOBreach,
   AnomalyDetected, and whatever comes next cannot ship undocumented.
7. no full-tensorize call (`compile_problem(...)` or
   `*._compile_tensor(...)`) outside the sanctioned cold-build and
   rebuild-fallback sites — the warm path's contract is that cluster
   deltas apply to the device-resident tensors (ops/resident.py) as
   scatter updates; a new call site re-tensorizing per tick silently
   reverts the resident win and must be consciously allowlisted.
8. no call into the sequential consolidation descent (`*._simulate(...)`
   or `*._consolidate_multi*(...)`) outside the sanctioned sites — the
   search's contract is that what-ifs flow through the batched
   population/verdict kernels, with the sequential path reserved for
   per-element fallbacks and the authoritative re-derivation of winning
   actions; a new call site quietly walking subsets host-side reverts
   the search promotion and must be consciously allowlisted.
9. no raw `jax.device_put(...)` outside the device observatory's counted
   seam (obs/device.py `DeviceObservatory.put`) — every host->device
   upload must count into `karpenter_device_transfer_bytes_total{site}`
   or the transfer accounting silently rots; a new upload site routes
   through `OBSERVATORY.put(site, ...)` or is consciously allowlisted
   by (file, qualified name).
10. every wire frame `method`/`type` literal the store plane sends
    through service/codec.py must appear (backticked) in
    docs/designs/store-scale.md — the protocol-vocabulary doc-rot
    guard: a new RPC method or pushed frame type cannot ship without
    the design doc saying what it means, which is also what keeps the
    mixed-version negotiation story reviewable.
"""

import ast
import importlib
import inspect
import pathlib
import pkgutil
import re
import typing

import karpenter_tpu


def _modules():
    for info in pkgutil.walk_packages(
        karpenter_tpu.__path__, prefix="karpenter_tpu."
    ):
        yield info.name


def test_all_modules_import():
    for name in _modules():
        importlib.import_module(name)


def test_annotations_resolve():
    failures = []
    for name in _modules():
        mod = importlib.import_module(name)
        targets = []
        for _, obj in vars(mod).items():
            if inspect.isfunction(obj) and obj.__module__ == name:
                targets.append(obj)
            elif inspect.isclass(obj) and obj.__module__ == name:
                targets.append(obj)
                for _, m in vars(obj).items():
                    if inspect.isfunction(m):
                        targets.append(m)
        for t in targets:
            try:
                typing.get_type_hints(t)
            except NameError as exc:
                failures.append(f"{name}.{getattr(t, '__qualname__', t)}: {exc}")
            except Exception:
                pass  # forward refs to runtime-only types are fine
    assert not failures, "\n".join(failures)


# the genuinely-wall-clock spots: the Clock abstraction itself is the one
# place allowed to read the wall.  (time.monotonic/perf_counter remain
# free — they measure host-side durations like batcher windows and solver
# phases, which no simulated clock can compress.)
_WALL_CLOCK_ALLOWLIST = {
    "karpenter_tpu/utils/clock.py",
}

_WALL_CLOCK_RE = re.compile(r"\btime\.(?:time|sleep)\s*\(")


def test_no_wall_clock_outside_clock_module():
    """Determinism contract: `time.time()`/`time.sleep()` only inside
    utils/clock.py (or the explicit allowlist).  Everything else takes an
    injected Clock, so the cluster simulator can run the whole stack on a
    FakeClock and replay a seed byte-identically."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    offenders = []
    for path in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(pkg_root.parent).as_posix()
        if rel in _WALL_CLOCK_ALLOWLIST:
            continue
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            code = line.split("#", 1)[0]
            if _WALL_CLOCK_RE.search(code):
                offenders.append(f"{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "wall-clock calls outside utils/clock.py (route through the "
        "injected Clock, or allowlist a genuinely-wall-clock spot):\n"
        + "\n".join(offenders)
    )


# the sanctioned scheduler.update call sites in controllers/: the
# provisioner's one-per-solve refresh, the deprovisioner's sequential
# simulation (the explicit fallback the batched path funnels through),
# and the batched evaluator's once-per-pass full-cluster sync.  Any NEW
# call site — especially one inside a per-candidate loop — must either
# go through TensorScheduler.evaluate_removals or be consciously added
# here.
_SCHEDULER_UPDATE_ALLOWLIST = {
    ("karpenter_tpu/controllers/provisioning.py", "Provisioner.provision"),
    ("karpenter_tpu/controllers/disruption.py",
     "DisruptionController._simulate"),
    ("karpenter_tpu/controllers/disruption.py",
     "_RemovalEvaluator._sync_scheduler"),
}


def scheduler_update_offenders(source: str, rel: str, allowlist):
    """AST scan for `<...scheduler...>.update(...)` calls: every call
    site must be allowlisted by (file, qualified name), and the report
    marks the ones lexically inside a for/while loop — the per-candidate
    serial-simulation pattern this rule exists to block."""
    tree = ast.parse(source)
    offenders = []

    class Visitor(ast.NodeVisitor):
        def __init__(self):
            self.scope = []  # class/function name stack
            self.loops = 0

        def _scoped(self, node, push):
            self.scope.append(push)
            self.generic_visit(node)
            self.scope.pop()

        def visit_ClassDef(self, node):
            self._scoped(node, node.name)

        def visit_FunctionDef(self, node):
            self._scoped(node, node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def _loop(self, node):
            self.loops += 1
            self.generic_visit(node)
            self.loops -= 1

        visit_For = visit_While = visit_AsyncFor = _loop

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "update":
                target = ast.unparse(f.value)
                if "scheduler" in target.lower():
                    qual = ".".join(self.scope)
                    if (rel, qual) not in allowlist:
                        where = "INSIDE A LOOP" if self.loops else "call"
                        offenders.append(
                            f"{rel}:{node.lineno}: {qual or '<module>'}: "
                            f"{target}.update(...) [{where}]"
                        )
            self.generic_visit(node)

    Visitor().visit(tree)
    return offenders


def test_no_scheduler_update_in_candidate_loops():
    """Serial-simulation guard: scheduler.update() in controllers/ only at
    the sanctioned sites — a per-candidate update loop re-compiles the
    whole problem per subset, which is exactly what the batched
    consolidation path (TensorScheduler.evaluate_removals) exists to
    avoid (docs/designs/consolidation-batching.md)."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    offenders = []
    for path in sorted((pkg_root / "controllers").glob("*.py")):
        rel = path.relative_to(pkg_root.parent).as_posix()
        offenders += scheduler_update_offenders(
            path.read_text(), rel, _SCHEDULER_UPDATE_ALLOWLIST
        )
    assert not offenders, (
        "unsanctioned scheduler.update() in controllers/ (batch the "
        "simulations through TensorScheduler.evaluate_removals, or "
        "allowlist a genuinely one-shot site):\n" + "\n".join(offenders)
    )


# the registry's recording verbs: a string literal metric name passed to
# any of these is a published series and must be documented.  (Reading
# verbs — counter/gauge/histogram/quantile — are deliberately included
# too: reading an undocumented series is the same rot.)
_REGISTRY_VERBS = frozenset(
    {
        "inc", "set", "observe", "time", "unset", "reset_gauge",
        "counter", "gauge", "histogram", "quantile",
    }
)


def documented_metric_names() -> set:
    """Every metric family named in docs/metrics.md (the generated
    reference page, tools/gen_metrics_doc.py)."""
    doc = (
        pathlib.Path(karpenter_tpu.__path__[0]).parent / "docs" / "metrics.md"
    )
    return set(re.findall(r"`(karpenter_[a-z0-9_]+)`", doc.read_text()))


def metric_doc_offenders(source: str, rel: str, documented: set):
    """AST scan: every `<anything>.<verb>("karpenter_...", ...)` call
    whose first argument is a string literal must name a documented
    metric family.  Dynamic names (f-strings, variables) are out of
    scope — the doc generator cannot see them either."""
    tree = ast.parse(source)
    offenders = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _REGISTRY_VERBS
            and node.args
        ):
            continue
        first = node.args[0]
        if not (
            isinstance(first, ast.Constant)
            and isinstance(first.value, str)
            and first.value.startswith("karpenter_")
        ):
            continue
        if first.value not in documented:
            offenders.append(
                f"{rel}:{node.lineno}: {first.value!r} passed to "
                f".{node.func.attr}() but absent from docs/metrics.md"
            )
    return offenders


def test_registry_metric_literals_documented():
    """Doc-rot guard: a metric literal reaching the registry without a
    docs/metrics.md entry means someone added a series and skipped
    `python -m karpenter_tpu.tools.gen_metrics_doc` — which also feeds
    the /metrics endpoint's HELP/TYPE catalog."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    documented = documented_metric_names()
    offenders = []
    for path in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(pkg_root.parent).as_posix()
        offenders += metric_doc_offenders(
            path.read_text(), rel, documented
        )
    assert not offenders, (
        "metric literals not documented (run `python -m "
        "karpenter_tpu.tools.gen_metrics_doc` to regenerate the page):\n"
        + "\n".join(offenders)
    )


def test_metric_doc_lint_has_teeth():
    """The checker actually fires on an undocumented literal and stays
    quiet on a documented one and on dynamic names."""
    documented = {"karpenter_known_total"}
    src = (
        "def f(reg, name):\n"
        "    reg.inc('karpenter_known_total')\n"
        "    reg.observe('karpenter_rogue_seconds', 1.0)\n"
        "    reg.inc(name)\n"  # dynamic: out of scope
    )
    hits = metric_doc_offenders(src, "karpenter_tpu/x.py", documented)
    assert len(hits) == 1 and "karpenter_rogue_seconds" in hits[0], hits


# rule 6: the event-emission verbs.  `Registry.event` and
# `EventLedger.emit` both take the event TYPE as their first positional
# argument; a CamelCase string literal there is a published ledger event
# and must be documented in the observability design's taxonomy.
_EVENT_VERBS = frozenset({"event", "emit"})

# event types are CamelCase identifiers (PodNominated, SLOBreach); the
# shape filter keeps unrelated `.event(tick, ...)` / `.emit(...)` call
# sites (ints, lowercase kinds) out of scope
_EVENT_TYPE_RE = re.compile(r"[A-Z][A-Za-z0-9]*")


def documented_event_types() -> set:
    """Every backticked CamelCase identifier in the observability design
    doc — a superset of the event taxonomy (class names in backticks are
    harmless extras; the lint only needs emitted literals ⊆ this set)."""
    doc = (
        pathlib.Path(karpenter_tpu.__path__[0]).parent
        / "docs" / "designs" / "observability.md"
    )
    return set(re.findall(r"`([A-Z][A-Za-z0-9]*)`", doc.read_text()))


def event_doc_offenders(source: str, rel: str, documented: set):
    """AST scan: every `<anything>.event("CamelCase", ...)` /
    `<anything>.emit("CamelCase", ...)` call must name a documented event
    type.  Dynamic types (variables, f-strings) are out of scope — the
    doc cannot enumerate them either."""
    tree = ast.parse(source)
    offenders = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _EVENT_VERBS
            and node.args
        ):
            continue
        first = node.args[0]
        if not (
            isinstance(first, ast.Constant)
            and isinstance(first.value, str)
            and _EVENT_TYPE_RE.fullmatch(first.value)
        ):
            continue
        if first.value not in documented:
            offenders.append(
                f"{rel}:{node.lineno}: event type {first.value!r} passed to "
                f".{node.func.attr}() but absent from "
                "docs/designs/observability.md"
            )
    return offenders


def test_ledger_event_literals_documented():
    """Doc-rot guard for the event taxonomy: an event-type literal
    reaching the ledger without a docs/designs/observability.md entry
    means someone added a decision event and skipped documenting what it
    means and where it fires."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    documented = documented_event_types()
    offenders = []
    for path in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(pkg_root.parent).as_posix()
        offenders += event_doc_offenders(path.read_text(), rel, documented)
    assert not offenders, (
        "ledger event types not documented (add them to the taxonomy in "
        "docs/designs/observability.md):\n" + "\n".join(offenders)
    )


def test_event_doc_lint_has_teeth():
    """The checker fires on an undocumented CamelCase literal, stays
    quiet on documented ones, dynamic types, and non-event `.event()`
    overloads (the trace writer's `.event(tick, kind, data)`)."""
    documented = {"NodeLaunched"}
    src = (
        "def f(reg, trace, t):\n"
        "    reg.event('NodeLaunched', claim='x')\n"
        "    reg.event('RogueEvent', oops=1)\n"
        "    reg.ledger.emit('AnotherRogue')\n"
        "    reg.event(t)\n"  # dynamic: out of scope
        "    trace.event(3, 'pod_create', {})\n"  # int + lowercase kind
    )
    hits = event_doc_offenders(src, "karpenter_tpu/x.py", documented)
    assert len(hits) == 2, hits
    assert "RogueEvent" in hits[0] and "AnotherRogue" in hits[1], hits


# rule 7: the sanctioned full-tensorize call sites.  The warm path's
# contract is that cluster deltas reach the device tensors as scatter
# updates (ops/resident.py); a full `compile_problem` / `_compile_tensor`
# belongs only at the cold build and the rebuild fallbacks the delta
# planner deliberately takes (catalog roll, shape change, churn past the
# midpoint).  Any NEW call site — especially in controllers/ or a solver
# warm path — must be consciously added here.
_FULL_TENSORIZE_ALLOWLIST = {
    # the wrapper itself: catalog bookkeeping + the one compile_problem
    ("karpenter_tpu/scheduling/solver.py",
     "TensorScheduler._compile_tensor"),
    # cold build / resident-miss rebuild in the solve path
    ("karpenter_tpu/scheduling/solver.py", "TensorScheduler._solve"),
    # direct compile+pack+decode kept for tests and custom callers
    ("karpenter_tpu/scheduling/solver.py",
     "TensorScheduler._solve_tensor"),
    # consolidation base: rebuild fallback when the resident layer misses
    ("karpenter_tpu/scheduling/solver.py",
     "TensorScheduler._build_removal_base"),
}

_FULL_TENSORIZE_NAMES = frozenset({"compile_problem", "_compile_tensor"})


def full_tensorize_offenders(source: str, rel: str, allowlist):
    """AST scan for full-tensorize calls: `compile_problem(...)` (bare or
    attribute) and `<anything>._compile_tensor(...)`.  Every call site
    must be allowlisted by (file, qualified name); hits lexically inside
    a for/while loop — the per-candidate re-tensorize antipattern — are
    called out."""
    tree = ast.parse(source)
    offenders = []

    class Visitor(ast.NodeVisitor):
        def __init__(self):
            self.scope = []
            self.loops = 0

        def _scoped(self, node, push):
            self.scope.append(push)
            self.generic_visit(node)
            self.scope.pop()

        def visit_ClassDef(self, node):
            self._scoped(node, node.name)

        def visit_FunctionDef(self, node):
            self._scoped(node, node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def _loop(self, node):
            self.loops += 1
            self.generic_visit(node)
            self.loops -= 1

        visit_For = visit_While = visit_AsyncFor = _loop

        def visit_Call(self, node):
            f = node.func
            name = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute)
                else None
            )
            if name in _FULL_TENSORIZE_NAMES:
                qual = ".".join(self.scope)
                if (rel, qual) not in allowlist:
                    where = "INSIDE A LOOP" if self.loops else "call"
                    offenders.append(
                        f"{rel}:{node.lineno}: {qual or '<module>'}: "
                        f"{name}(...) [{where}]"
                    )
            self.generic_visit(node)

    Visitor().visit(tree)
    return offenders


def test_no_full_tensorize_outside_sanctioned_sites():
    """Resident-tensor guard: a full tensorize in controllers/ or
    scheduling/ only at the sanctioned cold-build/rebuild-fallback sites
    — warm ticks must flow through the delta path (ops/resident.py,
    docs/designs/resident-tensors.md)."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    offenders = []
    for sub in ("controllers", "scheduling"):
        for path in sorted((pkg_root / sub).glob("*.py")):
            rel = path.relative_to(pkg_root.parent).as_posix()
            offenders += full_tensorize_offenders(
                path.read_text(), rel, _FULL_TENSORIZE_ALLOWLIST
            )
    assert not offenders, (
        "unsanctioned full-tensorize call (route warm updates through "
        "the resident delta path, or consciously allowlist a "
        "cold-build/rebuild site):\n" + "\n".join(offenders)
    )


def test_full_tensorize_lint_has_teeth():
    """The checker fires on bare and attribute call forms (tagging
    in-loop hits), and stays quiet on allowlisted sites."""
    bad = (
        "class S:\n"
        "    def warm(self, pods):\n"
        "        for batch in pods:\n"
        "            p = self._compile_tensor(batch, [])\n"
        "    def cold(self, pods):\n"
        "        return compile_problem(pods, [], {})\n"
    )
    hits = full_tensorize_offenders(
        bad, "karpenter_tpu/scheduling/x.py", _FULL_TENSORIZE_ALLOWLIST
    )
    assert len(hits) == 2, hits
    assert "INSIDE A LOOP" in hits[0] and "S.warm" in hits[0], hits
    assert "S.cold" in hits[1], hits
    ok = full_tensorize_offenders(
        bad, "karpenter_tpu/scheduling/x.py",
        {("karpenter_tpu/scheduling/x.py", "S.warm"),
         ("karpenter_tpu/scheduling/x.py", "S.cold")},
    )
    assert not ok, ok


# rule 8: the sanctioned sequential-descent call sites.  `_simulate` is
# the per-subset solver round-trip the batched kernels replaced; the
# population search may reach it only through the evaluator's lazy
# per-element fallback (`result`) and the winner's authoritative
# re-derivation (`vnode_for`), and the legacy drop-one descent
# (`_consolidate_multi_descent`) stays reachable only behind
# `use_population_search = False` via `_consolidate_multi`.  Any NEW
# call site — especially a loop of per-subset simulations — bypasses the
# batched search and must be consciously added here.
_SEQUENTIAL_DESCENT_ALLOWLIST = {
    # lazy per-element fallback: the one sanctioned batched->sequential seam
    ("karpenter_tpu/controllers/disruption.py", "_RemovalEvaluator.result"),
    # authoritative re-derivation of every winning action
    ("karpenter_tpu/controllers/disruption.py",
     "_RemovalEvaluator.vnode_for"),
    # the consolidation pass entry points (multi -> descent fallback)
    ("karpenter_tpu/controllers/disruption.py",
     "DisruptionController._consolidate"),
    ("karpenter_tpu/controllers/disruption.py",
     "DisruptionController._consolidate_multi"),
}

_SEQUENTIAL_DESCENT_NAMES = frozenset(
    {"_simulate", "_consolidate_multi", "_consolidate_multi_descent"}
)


def sequential_descent_offenders(source: str, rel: str, allowlist):
    """AST scan for sequential-descent calls: `<anything>._simulate(...)`
    and `<anything>._consolidate_multi[_descent](...)`.  Every call site
    must be allowlisted by (file, qualified name); hits lexically inside
    a for/while loop — the per-subset serial-walk antipattern — are
    called out."""
    tree = ast.parse(source)
    offenders = []

    class Visitor(ast.NodeVisitor):
        def __init__(self):
            self.scope = []
            self.loops = 0

        def _scoped(self, node, push):
            self.scope.append(push)
            self.generic_visit(node)
            self.scope.pop()

        def visit_ClassDef(self, node):
            self._scoped(node, node.name)

        def visit_FunctionDef(self, node):
            self._scoped(node, node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def _loop(self, node):
            self.loops += 1
            self.generic_visit(node)
            self.loops -= 1

        visit_For = visit_While = visit_AsyncFor = _loop

        def visit_Call(self, node):
            f = node.func
            name = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute)
                else None
            )
            if name in _SEQUENTIAL_DESCENT_NAMES:
                qual = ".".join(self.scope)
                if (rel, qual) not in allowlist:
                    where = "INSIDE A LOOP" if self.loops else "call"
                    offenders.append(
                        f"{rel}:{node.lineno}: {qual or '<module>'}: "
                        f"{name}(...) [{where}]"
                    )
            self.generic_visit(node)

    Visitor().visit(tree)
    return offenders


def test_no_sequential_descent_outside_sanctioned_sites():
    """Batched-search guard: the sequential `_simulate` / descent is
    reachable only from the allowlisted fallback and re-derivation sites
    — what-if evaluations must flow through the population/verdict
    kernels (docs/designs/consolidation-search.md fallback conditions),
    so a future code path cannot quietly walk subsets host-side."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    offenders = []
    for path in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(pkg_root.parent).as_posix()
        offenders += sequential_descent_offenders(
            path.read_text(), rel, _SEQUENTIAL_DESCENT_ALLOWLIST
        )
    assert not offenders, (
        "unsanctioned sequential-descent call (batch the what-ifs "
        "through evaluate_population/evaluate_removals, or consciously "
        "allowlist a fallback/re-derivation site):\n" + "\n".join(offenders)
    )


def test_sequential_descent_lint_has_teeth():
    """The checker fires on `_simulate` and descent calls (tagging
    in-loop hits), and stays quiet on allowlisted sites."""
    bad = (
        "class C:\n"
        "    def scan(self, cands):\n"
        "        for c in cands:\n"
        "            fits, price, vn = self._simulate([c])\n"
        "    def multi(self, ranked):\n"
        "        return self._consolidate_multi_descent(ranked, None)\n"
    )
    hits = sequential_descent_offenders(
        bad, "karpenter_tpu/controllers/x.py", _SEQUENTIAL_DESCENT_ALLOWLIST
    )
    assert len(hits) == 2, hits
    assert "INSIDE A LOOP" in hits[0] and "C.scan" in hits[0], hits
    assert "_consolidate_multi_descent" in hits[1] and "C.multi" in hits[1]
    ok = sequential_descent_offenders(
        bad, "karpenter_tpu/controllers/x.py",
        {("karpenter_tpu/controllers/x.py", "C.scan"),
         ("karpenter_tpu/controllers/x.py", "C.multi")},
    )
    assert not ok, ok


# rule 9: the counted-upload seam.  Transfer accounting
# (karpenter_device_transfer_bytes_total{site}) is only as complete as
# its coverage: every EXPLICIT host->device upload must route through
# `DeviceObservatory.put` (obs/device.py), which is therefore the one
# sanctioned raw `device_put` call site.  Implicit uploads (numpy
# arguments to jit calls) are counted at the dispatch seam and need no
# allowlisting.  Any NEW raw device_put — a fresh cache, a pinned
# tensor — must either take the seam or be consciously added here.
_DEVICE_PUT_ALLOWLIST = {
    ("karpenter_tpu/obs/device.py", "DeviceObservatory.put"),
}

_DEVICE_PUT_NAMES = frozenset({"device_put"})


def device_put_offenders(source: str, rel: str, allowlist):
    """AST scan for `jax.device_put(...)` / `<alias>.device_put(...)` /
    bare `device_put(...)` calls: every call site must be allowlisted by
    (file, qualified name); hits lexically inside a for/while loop — a
    per-item upload loop on the hot path — are called out."""
    tree = ast.parse(source)
    offenders = []

    class Visitor(ast.NodeVisitor):
        def __init__(self):
            self.scope = []
            self.loops = 0

        def _scoped(self, node, push):
            self.scope.append(push)
            self.generic_visit(node)
            self.scope.pop()

        def visit_ClassDef(self, node):
            self._scoped(node, node.name)

        def visit_FunctionDef(self, node):
            self._scoped(node, node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def _loop(self, node):
            self.loops += 1
            self.generic_visit(node)
            self.loops -= 1

        visit_For = visit_While = visit_AsyncFor = _loop

        def visit_Call(self, node):
            f = node.func
            name = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute)
                else None
            )
            if name in _DEVICE_PUT_NAMES:
                qual = ".".join(self.scope)
                if (rel, qual) not in allowlist:
                    where = "INSIDE A LOOP" if self.loops else "call"
                    offenders.append(
                        f"{rel}:{node.lineno}: {qual or '<module>'}: "
                        f"{name}(...) [{where}]"
                    )
            self.generic_visit(node)

    Visitor().visit(tree)
    return offenders


def test_no_raw_device_put_outside_counted_seam():
    """Transfer-accounting guard: raw device_put only inside the counted
    seam (obs/device.py DeviceObservatory.put) — an upload that bypasses
    it vanishes from karpenter_device_transfer_bytes_total{site}, and
    the bench's transfer columns and the doctor's transfer-regression
    rule quietly under-count."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    offenders = []
    for path in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(pkg_root.parent).as_posix()
        offenders += device_put_offenders(
            path.read_text(), rel, _DEVICE_PUT_ALLOWLIST
        )
    assert not offenders, (
        "raw device_put outside the counted seam (route the upload "
        "through OBSERVATORY.put(site, ...), or consciously allowlist "
        "it):\n" + "\n".join(offenders)
    )


def test_device_put_lint_has_teeth():
    """The checker fires on attribute and bare call forms (tagging
    in-loop hits), and stays quiet on allowlisted sites."""
    bad = (
        "class U:\n"
        "    def upload(self, arrays):\n"
        "        for a in arrays:\n"
        "            d = jax.device_put(a)\n"
        "    def pin(self, a):\n"
        "        return device_put(a)\n"
    )
    hits = device_put_offenders(
        bad, "karpenter_tpu/ops/x.py", _DEVICE_PUT_ALLOWLIST
    )
    assert len(hits) == 2, hits
    assert "INSIDE A LOOP" in hits[0] and "U.upload" in hits[0], hits
    assert "U.pin" in hits[1], hits
    ok = device_put_offenders(
        bad, "karpenter_tpu/ops/x.py",
        {("karpenter_tpu/ops/x.py", "U.upload"),
         ("karpenter_tpu/ops/x.py", "U.pin")},
    )
    assert not ok, ok


def test_scheduler_update_lint_has_teeth():
    """The checker actually fires: a synthetic per-candidate update loop
    is flagged (and tagged as in-loop), an allowlisted site is not."""
    bad = (
        "class C:\n"
        "    def scan(self, cands):\n"
        "        for c in cands:\n"
        "            s = self._scheduler.update(c)\n"
    )
    hits = scheduler_update_offenders(
        bad, "karpenter_tpu/controllers/x.py", _SCHEDULER_UPDATE_ALLOWLIST
    )
    assert len(hits) == 1 and "INSIDE A LOOP" in hits[0], hits
    ok = scheduler_update_offenders(
        bad, "karpenter_tpu/controllers/x.py",
        {("karpenter_tpu/controllers/x.py", "C.scan")},
    )
    assert not ok, ok


# rule 10: the store plane's wire vocabulary.  A dict literal with a
# "method" or "type" key and a string-literal value, in a store-plane
# file, IS a wire frame construction site — the literal is part of the
# protocol and must be documented in the store design doc's frame
# vocabulary.  (Receiving-side comparisons reuse the same literals, so
# guarding construction sites covers the vocabulary.)
_STORE_FRAME_FILES = (
    "karpenter_tpu/service/store_server.py",
    "karpenter_tpu/state/remote.py",
)

_STORE_FRAME_KEYS = frozenset({"method", "type"})


def documented_store_frame_literals() -> set:
    """Every backticked lowercase token in the store design doc — a
    superset of the frame vocabulary (prose backticks are harmless
    extras; the lint only needs sent literals ⊆ this set)."""
    doc = (
        pathlib.Path(karpenter_tpu.__path__[0]).parent
        / "docs" / "designs" / "store-scale.md"
    )
    return set(re.findall(r"`([a-z][a-z0-9_]*)`", doc.read_text()))


def store_frame_offenders(source: str, rel: str, documented: set):
    """AST scan: every dict literal carrying a ``"method"``/``"type"``
    key with a string-literal value must name a documented frame
    method/type.  Dynamic values (variables, f-strings) are out of
    scope — the doc cannot enumerate them either."""
    tree = ast.parse(source)
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for key, value in zip(node.keys, node.values):
            if not (
                isinstance(key, ast.Constant)
                and key.value in _STORE_FRAME_KEYS
                and isinstance(value, ast.Constant)
                and isinstance(value.value, str)
            ):
                continue
            if value.value not in documented:
                offenders.append(
                    f"{rel}:{value.lineno}: frame {key.value} literal "
                    f"{value.value!r} absent from "
                    "docs/designs/store-scale.md"
                )
    return offenders


def test_store_frame_literals_documented():
    """Doc-rot guard for the store protocol: a frame method/type literal
    sent through service/codec.py without a docs/designs/store-scale.md
    entry means someone grew the wire vocabulary and skipped documenting
    it."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    documented = documented_store_frame_literals()
    offenders = []
    for rel in _STORE_FRAME_FILES:
        path = pkg_root.parent / rel
        offenders += store_frame_offenders(path.read_text(), rel, documented)
    assert not offenders, (
        "store wire frame literals not documented (add them to the "
        "frame vocabulary in docs/designs/store-scale.md):\n"
        + "\n".join(offenders)
    )


def test_store_frame_lint_has_teeth():
    """The checker fires on undocumented method AND type literals, and
    stays quiet on documented ones and dynamic values."""
    documented = {"put", "events"}
    src = (
        "def f(m):\n"
        "    a = {'method': 'put', 'kind': 'Pod'}\n"
        "    b = {'type': 'events', 'seq': 1}\n"
        "    c = {'method': 'rogue_rpc'}\n"
        "    d = {'type': 'rogue_frame'}\n"
        "    e = {'method': m}\n"  # dynamic: out of scope
    )
    hits = store_frame_offenders(src, "karpenter_tpu/x.py", documented)
    assert len(hits) == 2, hits
    assert "rogue_rpc" in hits[0] and "rogue_frame" in hits[1], hits

# rule 11: thread construction in the controller layer is fenced to the
# pipeline seam.  The pipelined reconcile's determinism story depends on
# ALL controller-layer concurrency flowing through pipeline.py — the
# run_concurrently fan-out (degrades to serial in-order under the sim's
# workers=1 knobs) and the operator's declared dispatch/advance stages.
# A raw ThreadPoolExecutor/Thread in controllers/ or operator.py is an
# unscheduled side channel the twin-run and byte-identity proofs cannot
# see; any genuinely new need must be consciously allowlisted here.
_THREAD_SEAM_ALLOWLIST = {
    # the seam itself: the one sanctioned pool constructor
    ("karpenter_tpu/pipeline.py", "run_concurrently"),
}

_THREAD_CTOR_NAMES = frozenset({"Thread", "ThreadPoolExecutor"})


def thread_ctor_offenders(source: str, rel: str, allowlist):
    """AST scan for thread construction: ``Thread(...)`` /
    ``ThreadPoolExecutor(...)`` bare or as an attribute
    (``threading.Thread``, ``futures.ThreadPoolExecutor``).  Every call
    site must be allowlisted by (file, qualified name).  Locks /
    Events / Conditions stay free — the rule fences execution contexts,
    not synchronization primitives."""
    tree = ast.parse(source)
    offenders = []

    class Visitor(ast.NodeVisitor):
        def __init__(self):
            self.scope = []

        def _scoped(self, node, push):
            self.scope.append(push)
            self.generic_visit(node)
            self.scope.pop()

        def visit_ClassDef(self, node):
            self._scoped(node, node.name)

        def visit_FunctionDef(self, node):
            self._scoped(node, node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            f = node.func
            name = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute)
                else None
            )
            if name in _THREAD_CTOR_NAMES:
                qual = ".".join(self.scope)
                if (rel, qual) not in allowlist:
                    offenders.append(
                        f"{rel}:{node.lineno}: {qual or '<module>'}: "
                        f"{name}(...)"
                    )
            self.generic_visit(node)

    Visitor().visit(tree)
    return offenders


def test_no_raw_threads_outside_pipeline_seam():
    """Controller-layer concurrency flows through pipeline.py only: a
    raw thread in controllers/ or operator.py bypasses the pipelined
    schedule's determinism knobs (docs/designs/pipelined-reconcile.md)."""
    pkg_root = pathlib.Path(karpenter_tpu.__path__[0])
    scan = sorted((pkg_root / "controllers").glob("*.py"))
    scan += [pkg_root / "operator.py", pkg_root / "pipeline.py"]
    offenders = []
    for path in scan:
        rel = path.relative_to(pkg_root.parent).as_posix()
        offenders += thread_ctor_offenders(
            path.read_text(), rel, _THREAD_SEAM_ALLOWLIST
        )
    assert not offenders, (
        "raw thread construction in the controller layer (route the "
        "fan-out through pipeline.run_concurrently / declare a pipeline "
        "stage, or consciously allowlist it):\n" + "\n".join(offenders)
    )


def test_thread_seam_lint_has_teeth():
    """The checker fires on bare and attribute constructor forms, and
    stays quiet on allowlisted sites and on bare synchronization
    primitives."""
    bad = (
        "import threading\n"
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class C:\n"
        "    def fan(self, fns):\n"
        "        self._lock = threading.Lock()\n"
        "        t = threading.Thread(target=fns[0])\n"
        "    def pool(self, fns):\n"
        "        with ThreadPoolExecutor(max_workers=4) as p:\n"
        "            pass\n"
    )
    hits = thread_ctor_offenders(
        bad, "karpenter_tpu/controllers/x.py", _THREAD_SEAM_ALLOWLIST
    )
    assert len(hits) == 2, hits
    assert "C.fan" in hits[0] and "Thread" in hits[0], hits
    assert "C.pool" in hits[1], hits
    ok = thread_ctor_offenders(
        bad, "karpenter_tpu/controllers/x.py",
        {("karpenter_tpu/controllers/x.py", "C.fan"),
         ("karpenter_tpu/controllers/x.py", "C.pool")},
    )
    assert not ok, ok
