"""Lint gate, rebuilt on the static-analysis plane (karpenter_tpu/analysis/,
docs/designs/static-analysis.md).

What used to be ~1000 lines of ad-hoc AST walkers is now:

1. ONE parametrized runner: every registered rule runs against the real
   package through the engine and must report zero non-baselined
   findings — the machine-checked-invariants gate.
2. ONE generic teeth harness: for every rule, forge a violation in a
   synthetic tree and assert the rule fires (and that its allowlist
   silences it).  This replaces the six copy-pasted per-rule
   "lint has teeth" test bodies the old suite carried.
3. The CLI smoke: ``python -m karpenter_tpu lint --json`` over the real
   package in a fresh process — zero non-baselined findings, stable
   schema, exit-code contract (0 clean / 1 findings / 2 internal error).
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

from karpenter_tpu.analysis import (
    PackageSnapshot,
    RULES,
    run_rules,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def snap() -> PackageSnapshot:
    return PackageSnapshot.load()


# ------------------------------------------------------------ the gate
@pytest.mark.parametrize("rule", sorted(RULES))
def test_package_is_clean(snap, rule):
    """Every rule, zero non-baselined findings on the real package —
    each guarantee in README's machine-checked-invariants table holds."""
    live, _suppressed = run_rules(snap, rule_names=[rule])
    assert not live, "\n".join(f.render() for f in live)


# ------------------------------------------------------- teeth harness
def forge(tmp_path, files: dict, docs: dict = None) -> PackageSnapshot:
    """Forge a synthetic package tree (and optional repo docs) and
    snapshot it.  The package dir is named ``forged`` — scope predicates
    match on package-relative paths, so ``controllers/x.py`` etc. scope
    exactly like the real tree."""
    pkg = tmp_path / "forged"
    for rel, src in files.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    for rel, text in (docs or {}).items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(text)
    return PackageSnapshot(pkg)


# One case per rule: (files, docs, expected message substrings, an
# allowlist that silences every finding).  The harness asserts BOTH
# directions — fires without the allowlist, silent with it — which is
# what "teeth" means.
TEETH = {
    "wall-clock": dict(
        files={"controllers/x.py": "import time\nnow = time.time()\n"},
        expect=["wall-clock call"],
        silence=frozenset({"forged/controllers/x.py"}),
    ),
    "scheduler-update": dict(
        files={
            "controllers/x.py": (
                "class C:\n"
                "    def scan(self, cands):\n"
                "        for c in cands:\n"
                "            s = self._scheduler.update(c)\n"
            )
        },
        expect=["INSIDE A LOOP", "C.scan"],
        silence=frozenset({("forged/controllers/x.py", "C.scan")}),
    ),
    "metric-doc": dict(
        files={
            "x.py": (
                "def f(reg, name):\n"
                "    reg.inc('karpenter_known_total')\n"
                "    reg.observe('karpenter_rogue_seconds', 1.0)\n"
                "    reg.inc(name)\n"  # dynamic: out of scope
            )
        },
        docs={"docs/metrics.md": "`karpenter_known_total`\n"},
        expect=["karpenter_rogue_seconds"],
        silence=frozenset({"karpenter_rogue_seconds"}),
    ),
    "event-doc": dict(
        files={
            "x.py": (
                "def f(reg, trace, t):\n"
                "    reg.event('NodeLaunched', claim='x')\n"
                "    reg.event('RogueEvent', oops=1)\n"
                "    reg.ledger.emit('AnotherRogue')\n"
                "    reg.event(t)\n"  # dynamic: out of scope
                "    trace.event(3, 'pod_create', {})\n"  # int + lowercase
            )
        },
        docs={"docs/designs/observability.md": "`NodeLaunched`\n"},
        expect=["RogueEvent", "AnotherRogue"],
        silence=frozenset({"RogueEvent", "AnotherRogue"}),
    ),
    "full-tensorize": dict(
        files={
            "scheduling/x.py": (
                "class S:\n"
                "    def warm(self, pods):\n"
                "        for batch in pods:\n"
                "            p = self._compile_tensor(batch, [])\n"
                "    def cold(self, pods):\n"
                "        return compile_problem(pods, [], {})\n"
            )
        },
        expect=["INSIDE A LOOP", "S.warm", "S.cold"],
        silence=frozenset(
            {
                ("forged/scheduling/x.py", "S.warm"),
                ("forged/scheduling/x.py", "S.cold"),
            }
        ),
    ),
    "sequential-descent": dict(
        files={
            "controllers/x.py": (
                "class C:\n"
                "    def scan(self, cands):\n"
                "        for c in cands:\n"
                "            fits, price, vn = self._simulate([c])\n"
                "    def multi(self, ranked):\n"
                "        return self._consolidate_multi_descent(ranked, None)\n"
            )
        },
        expect=["INSIDE A LOOP", "C.scan", "_consolidate_multi_descent"],
        silence=frozenset(
            {
                ("forged/controllers/x.py", "C.scan"),
                ("forged/controllers/x.py", "C.multi"),
            }
        ),
    ),
    "device-put": dict(
        files={
            "ops/x.py": (
                "class U:\n"
                "    def upload(self, arrays):\n"
                "        for a in arrays:\n"
                "            d = jax.device_put(a)\n"
                "    def pin(self, a):\n"
                "        return device_put(a)\n"
            )
        },
        expect=["INSIDE A LOOP", "U.upload", "U.pin"],
        silence=frozenset(
            {
                ("forged/ops/x.py", "U.upload"),
                ("forged/ops/x.py", "U.pin"),
            }
        ),
    ),
    "store-frame": dict(
        files={
            "state/remote.py": (
                "def f(m):\n"
                "    a = {'method': 'put', 'kind': 'Pod'}\n"
                "    b = {'type': 'events', 'seq': 1}\n"
                "    c = {'method': 'rogue_rpc'}\n"
                "    d = {'type': 'rogue_frame'}\n"
                "    e = {'method': m}\n"  # dynamic: out of scope
            )
        },
        docs={"docs/designs/store-scale.md": "`put` `events`\n"},
        expect=["rogue_rpc", "rogue_frame"],
        silence=frozenset({"rogue_rpc", "rogue_frame"}),
    ),
    "thread-seam": dict(
        files={
            "controllers/x.py": (
                "import threading\n"
                "from concurrent.futures import ThreadPoolExecutor\n"
                "class C:\n"
                "    def fan(self, fns):\n"
                "        self._lock = threading.Lock()\n"  # sync prims free
                "    def spawn(self, fns):\n"
                "        t = threading.Thread(target=fns[0])\n"
                "    def pool(self, fns):\n"
                "        with ThreadPoolExecutor(max_workers=4) as p:\n"
                "            pass\n"
            )
        },
        expect=["C.spawn", "Thread", "C.pool"],
        silence=frozenset(
            {
                ("forged/controllers/x.py", "C.spawn"),
                ("forged/controllers/x.py", "C.pool"),
            }
        ),
    ),
    # ---- the three whole-program analyzers (acceptance teeth)
    "lock-blocking": dict(
        files={
            "service/x.py": (
                "import threading\n"
                "from forged.service.codec import send_frame\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "    def push(self, sock, payload):\n"
                "        with self._lock:\n"
                "            self._emit(sock, payload)\n"
                "    def _emit(self, sock, payload):\n"
                "        send_frame(sock, payload)\n"
            ),
            "service/codec.py": "def send_frame(sock, b):\n    sock.sendall(b)\n",
        },
        expect=["send_frame", "S._lock", "S.push"],
        silence=frozenset({("forged/service/x.py", "S.push")}),
    ),
    "lock-order": dict(
        # the forged inversion: A->B in one method, B->A in another
        files={
            "service/x.py": (
                "import threading\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._a = threading.Lock()\n"
                "        self._b = threading.Lock()\n"
                "    def forward(self):\n"
                "        with self._a:\n"
                "            with self._b:\n"
                "                pass\n"
                "    def backward(self):\n"
                "        with self._b:\n"
                "            with self._a:\n"
                "                pass\n"
            )
        },
        expect=["lock order inversion", "S._a", "S._b"],
        silence=frozenset({"S._a|S._b"}),
    ),
    "determinism-reachability": dict(
        # the forged clock-reachable digest path: digest -> helper ->
        # time.time(), two hops from the byte-compared root
        files={
            "sim/trace.py": (
                "import time\n"
                "def stamp():\n"
                "    return time.time()\n"
                "class TraceWriter:\n"
                "    def digest(self, tick, env):\n"
                "        return {'tick': tick, 'at': stamp()}\n"
            )
        },
        expect=["wall clock time.time()", "stamp", "TraceWriter.digest"],
        silence=frozenset({"forged/sim/trace.py"}),
    ),
    "tracer-safety": dict(
        # the forged unseamed jit dispatch + an impure traced body
        files={
            "ops/x.py": (
                "import jax\n"
                "import time\n"
                "@jax.jit\n"
                "def kernel(x):\n"
                "    print('tracing')\n"
                "    t = time.time()\n"
                "    x[0] = t\n"
                "    return x\n"
                "def solve(x):\n"
                "    return kernel(x)\n"
            )
        },
        expect=[
            "print(...) inside traced body",
            "time.time(...) inside traced body",
            "assigns into a traced parameter",
            "bypasses the counted seam",
        ],
        silence=None,  # impure bodies have no allowlist: only the call
        # site is allowlistable (checked separately below)
    ),
}


@pytest.mark.parametrize("rule", sorted(TEETH))
def test_rule_has_teeth(tmp_path, rule):
    """Generic forge-a-violation harness: the rule fires on a synthetic
    violation and its allowlist silences it — one body for all rules,
    replacing the six duplicated per-rule copies."""
    case = TEETH[rule]
    forged = forge(tmp_path, case["files"], case.get("docs"))
    live, _ = run_rules(
        forged, rule_names=[rule], allowlists={rule: frozenset()}
    )
    text = "\n".join(f.render() for f in live)
    assert live, f"{rule}: forged violation did not fire"
    for fragment in case["expect"]:
        assert fragment in text, f"{rule}: {fragment!r} not in:\n{text}"
    if case["silence"] is None:
        return
    silenced, _ = run_rules(
        forged, rule_names=[rule], allowlists={rule: case["silence"]}
    )
    assert not silenced, "\n".join(f.render() for f in silenced)


def test_tracer_safety_call_site_allowlist(tmp_path):
    """The seam-dispatch half of tracer-safety is allowlistable by
    (file, qualname) — the impure-body half deliberately is not."""
    forged = forge(
        tmp_path,
        {
            "ops/x.py": (
                "import jax\n"
                "@jax.jit\n"
                "def kernel(x):\n"
                "    return x\n"
                "def solve(x):\n"
                "    return kernel(x)\n"
            )
        },
    )
    live, _ = run_rules(
        forged, rule_names=["tracer-safety"],
        allowlists={"tracer-safety": frozenset()},
    )
    assert len(live) == 1 and "bypasses the counted seam" in live[0].message
    silenced, _ = run_rules(
        forged, rule_names=["tracer-safety"],
        allowlists={"tracer-safety": frozenset({("forged/ops/x.py", "solve")})},
    )
    assert not silenced


def test_baseline_suppresses_by_fingerprint(tmp_path):
    """A baselined fingerprint moves the finding to the suppressed list
    without deleting the signal; unrelated findings stay live."""
    forged = forge(
        tmp_path, {"x.py": "import time\na = time.time()\nb = time.sleep(1)\n"}
    )
    live, _ = run_rules(
        forged, rule_names=["wall-clock"], allowlists={"wall-clock": frozenset()}
    )
    assert len(live) == 2
    baseline = {live[0].fingerprint: "known"}
    live2, suppressed = run_rules(
        forged, rule_names=["wall-clock"],
        allowlists={"wall-clock": frozenset()}, baseline=baseline,
    )
    assert len(live2) == 1 and len(suppressed) == 1
    assert suppressed[0].fingerprint == live[0].fingerprint


# ------------------------------------------------------------ CLI smoke
def test_cli_smoke_real_package_is_clean():
    """Tier-1 smoke (the CI gate): ``python -m karpenter_tpu lint
    --json`` over the real package in a fresh process reports zero
    non-baselined findings and exits 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "karpenter_tpu", "lint", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/local/bin:/usr/bin:/bin"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["version"] == 1
    assert report["counts"]["findings"] == 0, report["findings"]
    assert report["findings"] == []
    # every registered rule ran
    assert report["rules"] == sorted(RULES)


def test_cli_exit_codes(tmp_path):
    """0 clean / 1 findings / 2 internal error, and --rule filtering."""
    from karpenter_tpu.analysis.cli import main as lint_main

    pkg = tmp_path / "forged"
    (pkg / "sub").mkdir(parents=True)
    (pkg / "sub" / "x.py").write_text("import time\nnow = time.time()\n")
    assert lint_main(["--root", str(pkg), "--rule", "wall-clock"]) == 1
    assert lint_main(["--root", str(pkg), "--rule", "store-frame"]) == 0
    # unknown rule = the checker itself is misconfigured: internal error
    assert lint_main(["--root", str(pkg), "--rule", "no-such-rule"]) == 2
