"""Lint gate, rebuilt on the static-analysis plane (karpenter_tpu/analysis/,
docs/designs/static-analysis.md).

What used to be ~1000 lines of ad-hoc AST walkers is now:

1. ONE parametrized runner: every registered rule runs against the real
   package through the engine and must report zero non-baselined
   findings — the machine-checked-invariants gate.
2. ONE generic teeth harness: for every rule, forge a violation in a
   synthetic tree and assert the rule fires (and that its allowlist
   silences it).  This replaces the six copy-pasted per-rule
   "lint has teeth" test bodies the old suite carried.
3. The CLI smoke: ``python -m karpenter_tpu lint --json`` over the real
   package in a fresh process — zero non-baselined findings, stable
   schema, exit-code contract (0 clean / 1 findings / 2 internal error).
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

from karpenter_tpu.analysis import (
    PackageSnapshot,
    RULES,
    run_rules,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def snap() -> PackageSnapshot:
    return PackageSnapshot.load()


# ------------------------------------------------------------ the gate
@pytest.mark.parametrize("rule", sorted(RULES))
def test_package_is_clean(snap, rule):
    """Every rule, zero non-baselined findings on the real package —
    each guarantee in README's machine-checked-invariants table holds."""
    live, _suppressed = run_rules(snap, rule_names=[rule])
    assert not live, "\n".join(f.render() for f in live)


# ------------------------------------------------------- teeth harness
def forge(tmp_path, files: dict, docs: dict = None) -> PackageSnapshot:
    """Forge a synthetic package tree (and optional repo docs) and
    snapshot it.  The package dir is named ``forged`` — scope predicates
    match on package-relative paths, so ``controllers/x.py`` etc. scope
    exactly like the real tree."""
    pkg = tmp_path / "forged"
    for rel, src in files.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    for rel, text in (docs or {}).items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(text)
    return PackageSnapshot(pkg)


# One case per rule: (files, docs, expected message substrings, an
# allowlist that silences every finding).  The harness asserts BOTH
# directions — fires without the allowlist, silent with it — which is
# what "teeth" means.
TEETH = {
    "wall-clock": dict(
        files={"controllers/x.py": "import time\nnow = time.time()\n"},
        expect=["wall-clock call"],
        silence=frozenset({"forged/controllers/x.py"}),
    ),
    "scheduler-update": dict(
        files={
            "controllers/x.py": (
                "class C:\n"
                "    def scan(self, cands):\n"
                "        for c in cands:\n"
                "            s = self._scheduler.update(c)\n"
            )
        },
        expect=["INSIDE A LOOP", "C.scan"],
        silence=frozenset({("forged/controllers/x.py", "C.scan")}),
    ),
    "metric-doc": dict(
        files={
            "x.py": (
                "def f(reg, name):\n"
                "    reg.inc('karpenter_known_total')\n"
                "    reg.observe('karpenter_rogue_seconds', 1.0)\n"
                "    reg.inc(name)\n"  # dynamic: out of scope
            )
        },
        docs={"docs/metrics.md": "`karpenter_known_total`\n"},
        expect=["karpenter_rogue_seconds"],
        silence=frozenset({"karpenter_rogue_seconds"}),
    ),
    "event-doc": dict(
        files={
            "x.py": (
                "def f(reg, trace, t):\n"
                "    reg.event('NodeLaunched', claim='x')\n"
                "    reg.event('RogueEvent', oops=1)\n"
                "    reg.ledger.emit('AnotherRogue')\n"
                "    reg.event(t)\n"  # dynamic: out of scope
                "    trace.event(3, 'pod_create', {})\n"  # int + lowercase
            )
        },
        docs={"docs/designs/observability.md": "`NodeLaunched`\n"},
        expect=["RogueEvent", "AnotherRogue"],
        silence=frozenset({"RogueEvent", "AnotherRogue"}),
    ),
    "full-tensorize": dict(
        files={
            "scheduling/x.py": (
                "class S:\n"
                "    def warm(self, pods):\n"
                "        for batch in pods:\n"
                "            p = self._compile_tensor(batch, [])\n"
                "    def cold(self, pods):\n"
                "        return compile_problem(pods, [], {})\n"
            )
        },
        expect=["INSIDE A LOOP", "S.warm", "S.cold"],
        silence=frozenset(
            {
                ("forged/scheduling/x.py", "S.warm"),
                ("forged/scheduling/x.py", "S.cold"),
            }
        ),
    ),
    "sequential-descent": dict(
        files={
            "controllers/x.py": (
                "class C:\n"
                "    def scan(self, cands):\n"
                "        for c in cands:\n"
                "            fits, price, vn = self._simulate([c])\n"
                "    def multi(self, ranked):\n"
                "        return self._consolidate_multi_descent(ranked, None)\n"
            )
        },
        expect=["INSIDE A LOOP", "C.scan", "_consolidate_multi_descent"],
        silence=frozenset(
            {
                ("forged/controllers/x.py", "C.scan"),
                ("forged/controllers/x.py", "C.multi"),
            }
        ),
    ),
    "device-put": dict(
        files={
            "ops/x.py": (
                "class U:\n"
                "    def upload(self, arrays):\n"
                "        for a in arrays:\n"
                "            d = jax.device_put(a)\n"
                "    def pin(self, a):\n"
                "        return device_put(a)\n"
            )
        },
        expect=["INSIDE A LOOP", "U.upload", "U.pin"],
        silence=frozenset(
            {
                ("forged/ops/x.py", "U.upload"),
                ("forged/ops/x.py", "U.pin"),
            }
        ),
    ),
    "store-frame": dict(
        files={
            "state/remote.py": (
                "def f(m):\n"
                "    a = {'method': 'put', 'kind': 'Pod'}\n"
                "    b = {'type': 'events', 'seq': 1}\n"
                "    c = {'method': 'rogue_rpc'}\n"
                "    d = {'type': 'rogue_frame'}\n"
                "    e = {'method': m}\n"  # dynamic: out of scope
            )
        },
        docs={"docs/designs/store-scale.md": "`put` `events`\n"},
        expect=["rogue_rpc", "rogue_frame"],
        silence=frozenset({"rogue_rpc", "rogue_frame"}),
    ),
    "thread-seam": dict(
        files={
            "controllers/x.py": (
                "import threading\n"
                "from concurrent.futures import ThreadPoolExecutor\n"
                "class C:\n"
                "    def fan(self, fns):\n"
                "        self._lock = threading.Lock()\n"  # sync prims free
                "    def spawn(self, fns):\n"
                "        t = threading.Thread(target=fns[0])\n"
                "    def pool(self, fns):\n"
                "        with ThreadPoolExecutor(max_workers=4) as p:\n"
                "            pass\n"
            )
        },
        expect=["C.spawn", "Thread", "C.pool"],
        silence=frozenset(
            {
                ("forged/controllers/x.py", "C.spawn"),
                ("forged/controllers/x.py", "C.pool"),
            }
        ),
    ),
    # ---- the three whole-program analyzers (acceptance teeth)
    "lock-blocking": dict(
        files={
            "service/x.py": (
                "import threading\n"
                "from forged.service.codec import send_frame\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "    def push(self, sock, payload):\n"
                "        with self._lock:\n"
                "            self._emit(sock, payload)\n"
                "    def _emit(self, sock, payload):\n"
                "        send_frame(sock, payload)\n"
            ),
            "service/codec.py": "def send_frame(sock, b):\n    sock.sendall(b)\n",
        },
        expect=["send_frame", "S._lock", "S.push"],
        silence=frozenset({("forged/service/x.py", "S.push")}),
    ),
    "lock-order": dict(
        # the forged inversion: A->B in one method, B->A in another
        files={
            "service/x.py": (
                "import threading\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._a = threading.Lock()\n"
                "        self._b = threading.Lock()\n"
                "    def forward(self):\n"
                "        with self._a:\n"
                "            with self._b:\n"
                "                pass\n"
                "    def backward(self):\n"
                "        with self._b:\n"
                "            with self._a:\n"
                "                pass\n"
            )
        },
        expect=["lock order inversion", "S._a", "S._b"],
        silence=frozenset({"S._a|S._b"}),
    ),
    "determinism-reachability": dict(
        # the forged clock-reachable digest path: digest -> helper ->
        # time.time(), two hops from the byte-compared root
        files={
            "sim/trace.py": (
                "import time\n"
                "def stamp():\n"
                "    return time.time()\n"
                "class TraceWriter:\n"
                "    def digest(self, tick, env):\n"
                "        return {'tick': tick, 'at': stamp()}\n"
            )
        },
        expect=["wall clock time.time()", "stamp", "TraceWriter.digest"],
        silence=frozenset({"forged/sim/trace.py"}),
    ),
    "settings-flow": dict(
        # three fields: "wired" is read + charted (clean), "dead_knob"
        # is charted but never read (fires, allowlistable), "uncharted"
        # is read but missing from values.yaml + configmap (fires —
        # silenced here only via the chart docs, so the silence set
        # covers dead_knob alone and the forged chart carries uncharted)
        files={
            "api/settings.py": (
                "class Settings:\n"
                "    wired: bool = True\n"
                "    dead_knob: int = 0\n"
                "    def validate(self):\n"
                "        return self.dead_knob\n"  # reads here don't count
            ),
            "operator.py": (
                "def run(settings):\n"
                "    return settings.wired\n"
            ),
        },
        docs={
            "deploy/chart/values.yaml": (
                "settings:\n  wired: \"true\"\n  dead_knob: \"0\"\n"
            ),
            "deploy/chart/templates/configmap.yaml": (
                '{ "wired": x, "dead_knob": y }\n'
            ),
        },
        expect=["dead_knob", "never read", "dead twin knob"],
        silence=frozenset({"dead_knob"}),
    ),
    "lock-seam": dict(
        files={
            "service/x.py": (
                "import threading\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
            ),
        },
        expect=["S._lock", "constructed raw", "make_lock"],
        silence=frozenset({("forged/service/x.py", "S._lock")}),
    ),
    "service-tenant-metrics": dict(
        # four emissions: tenant-labeled + documented (clean),
        # tenant-blind (fires), undocumented (fires), and one OUTSIDE
        # service/ (out of scope even when blind — the rule fences the
        # service plane, not the world)
        files={
            "service/x.py": (
                "def f(reg, t, name):\n"
                "    reg.inc('karpenter_service_good_total',"
                " {'tenant': t})\n"
                "    reg.inc('karpenter_service_blind_total',"
                " {'method': 'pack'})\n"
                "    reg.set('karpenter_service_dark_bytes', 1,"
                " {'tenant': t})\n"
                "    reg.inc(name)\n"  # dynamic: out of scope
            ),
            "obs/y.py": (
                "def g(reg):\n"
                "    reg.inc('karpenter_service_elsewhere_total')\n"
            ),
        },
        docs={
            "docs/metrics.md": (
                "`karpenter_service_good_total`\n"
                "`karpenter_service_blind_total`\n"
            )
        },
        expect=[
            "karpenter_service_blind_total",
            "without a 'tenant' label",
            "karpenter_service_dark_bytes",
            "absent from docs/metrics.md",
        ],
        silence=frozenset(
            {"forged/service/x.py", "karpenter_service_dark_bytes"}
        ),
    ),
    "tracer-safety": dict(
        # the forged unseamed jit dispatch + an impure traced body
        files={
            "ops/x.py": (
                "import jax\n"
                "import time\n"
                "@jax.jit\n"
                "def kernel(x):\n"
                "    print('tracing')\n"
                "    t = time.time()\n"
                "    x[0] = t\n"
                "    return x\n"
                "def solve(x):\n"
                "    return kernel(x)\n"
            )
        },
        expect=[
            "print(...) inside traced body",
            "time.time(...) inside traced body",
            "assigns into a traced parameter",
            "bypasses the counted seam",
        ],
        silence=None,  # impure bodies have no allowlist: only the call
        # site is allowlistable (checked separately below)
    ),
}


@pytest.mark.parametrize("rule", sorted(TEETH))
def test_rule_has_teeth(tmp_path, rule):
    """Generic forge-a-violation harness: the rule fires on a synthetic
    violation and its allowlist silences it — one body for all rules,
    replacing the six duplicated per-rule copies."""
    case = TEETH[rule]
    forged = forge(tmp_path, case["files"], case.get("docs"))
    live, _ = run_rules(
        forged, rule_names=[rule], allowlists={rule: frozenset()}
    )
    text = "\n".join(f.render() for f in live)
    assert live, f"{rule}: forged violation did not fire"
    for fragment in case["expect"]:
        assert fragment in text, f"{rule}: {fragment!r} not in:\n{text}"
    if case["silence"] is None:
        return
    silenced, _ = run_rules(
        forged, rule_names=[rule], allowlists={rule: case["silence"]}
    )
    assert not silenced, "\n".join(f.render() for f in silenced)


def test_full_tensorize_deny_fence(tmp_path):
    """Rule 7's deny fence: scheduling/fastpath.py may NEVER call the
    tensorize entry points, and — unlike every other finding — no
    allowlist entry can sanction it.  An attempted entry is itself an
    additional finding, so the fence cannot be quietly weakened."""
    forged = forge(
        tmp_path,
        {
            "scheduling/fastpath.py": (
                "def admit(s):\n"
                "    return compile_problem(s, [], {})\n"
            )
        },
    )
    live, _ = run_rules(
        forged, rule_names=["full-tensorize"],
        allowlists={"full-tensorize": frozenset()},
    )
    text = "\n".join(f.render() for f in live)
    assert live, "deny fence did not fire"
    assert "DENIED" in text
    # the allowlist is powerless: the call STILL fires, and the entry
    # itself becomes a finding naming the attempted exception
    entry = ("forged/scheduling/fastpath.py", "admit")
    still, _ = run_rules(
        forged, rule_names=["full-tensorize"],
        allowlists={"full-tensorize": frozenset({entry})},
    )
    text2 = "\n".join(f.render() for f in still)
    assert len(still) >= 2, text2
    assert "DENIED" in text2
    assert "no exception" in text2


def test_tracer_safety_call_site_allowlist(tmp_path):
    """The seam-dispatch half of tracer-safety is allowlistable by
    (file, qualname) — the impure-body half deliberately is not."""
    forged = forge(
        tmp_path,
        {
            "ops/x.py": (
                "import jax\n"
                "@jax.jit\n"
                "def kernel(x):\n"
                "    return x\n"
                "def solve(x):\n"
                "    return kernel(x)\n"
            )
        },
    )
    live, _ = run_rules(
        forged, rule_names=["tracer-safety"],
        allowlists={"tracer-safety": frozenset()},
    )
    assert len(live) == 1 and "bypasses the counted seam" in live[0].message
    silenced, _ = run_rules(
        forged, rule_names=["tracer-safety"],
        allowlists={"tracer-safety": frozenset({("forged/ops/x.py", "solve")})},
    )
    assert not silenced


def test_lock_seam_name_must_match_static_identity(tmp_path):
    """The seam's name argument is the witness<->static vocabulary; a
    drifted name is a finding and is deliberately NOT allowlistable
    (like impure traced bodies) — there is no sound reason for a lock
    to lie about its identity."""
    forged = forge(
        tmp_path,
        {
            "service/x.py": (
                "from forged.analysis.sanitizer import make_lock\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._lock = make_lock('Wrong.name')\n"
            ),
        },
    )
    live, _ = run_rules(
        forged, rule_names=["lock-seam"],
        allowlists={"lock-seam": frozenset()},
    )
    assert len(live) == 1 and "static identity 'S._lock'" in live[0].message
    # the correct name is clean
    ok = forge(
        tmp_path / "ok",
        {
            "service/x.py": (
                "from forged.analysis.sanitizer import make_lock\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._lock = make_lock('S._lock')\n"
            ),
        },
    )
    clean, _ = run_rules(
        ok, rule_names=["lock-seam"], allowlists={"lock-seam": frozenset()}
    )
    assert not clean, "\n".join(f.render() for f in clean)


def test_lock_seam_catches_from_threading_import_form(tmp_path):
    """`from threading import Lock; self._l = Lock()` is just as raw as
    `threading.Lock()` — the fence is not bypassable by import style."""
    forged = forge(
        tmp_path,
        {
            "service/x.py": (
                "from threading import Lock, RLock as RL\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._l = Lock()\n"
                "        self._r = RL()\n"
            ),
        },
    )
    live, _ = run_rules(
        forged, rule_names=["lock-seam"],
        allowlists={"lock-seam": frozenset()},
    )
    messages = "\n".join(f.message for f in live)
    assert len(live) == 2, messages
    assert "S._l" in messages and "S._r" in messages


def test_lock_seam_nested_class_attributed_once(tmp_path):
    """A nested class's lock belongs to the INNER class: no phantom
    Outer.attr identity, no bogus name-mismatch, no double finding."""
    forged = forge(
        tmp_path,
        {
            "service/x.py": (
                "import threading\n"
                "class Outer:\n"
                "    class Inner:\n"
                "        def __init__(self):\n"
                "            self._lock = make_lock('Inner._lock')\n"
                "            self._raw = threading.Lock()\n"
            ),
        },
    )
    live, _ = run_rules(
        forged, rule_names=["lock-seam"],
        allowlists={"lock-seam": frozenset()},
    )
    # exactly ONE finding: the raw construction, attributed to Inner;
    # the correctly-named seam lock is clean
    assert len(live) == 1, "\n".join(f.render() for f in live)
    assert "Inner._raw" in live[0].message
    from karpenter_tpu.analysis.locks import build_lock_model

    model = build_lock_model(forged)
    assert ("Inner", "_lock") in model.owners
    assert ("Outer", "_lock") not in model.owners


def test_settings_flow_scoped_to_the_settings_block(tmp_path):
    """A Settings field named like some OTHER chart key (`replicas`)
    must not satisfy the values.yaml presence check by accident."""
    forged = forge(
        tmp_path,
        {
            "api/settings.py": (
                "class Settings:\n"
                "    replicas: int = 1\n"
            ),
            "operator.py": "def run(s):\n    return s.replicas\n",
        },
        docs={
            "deploy/chart/values.yaml": (
                "replicas: 1\nsettings:\n  cluster_name: \"\"\n"
            ),
            "deploy/chart/templates/configmap.yaml": '{ "replicas": x }\n',
        },
    )
    live, _ = run_rules(
        forged, rule_names=["settings-flow"],
        allowlists={"settings-flow": frozenset()},
    )
    assert len(live) == 1, "\n".join(f.render() for f in live)
    assert "missing from deploy/chart/values.yaml" in live[0].message


def test_settings_flow_nested_values_route(tmp_path):
    """The structured-values exposure route (the service.multiTenant.*
    shape): a field absent from the settings: block is CLEAN when its
    configmap line references .Values paths that resolve in values.yaml,
    and fires when a referenced path does not resolve — the nested
    spelling of the same dead-knob guarantee."""
    files = {
        "api/settings.py": (
            "class Settings:\n"
            "    nested_knob: bool = False\n"
            "    broken_knob: int = 0\n"
        ),
        "operator.py": (
            "def run(s):\n"
            "    return (s.nested_knob, s.broken_knob)\n"
        ),
    }
    docs = {
        "deploy/chart/values.yaml": (
            "service:\n"
            "  multiTenant:\n"
            "    enabled: \"false\"  # comment\n"
            "settings:\n"
            "  cluster_name: \"\"\n"
        ),
        "deploy/chart/templates/configmap.yaml": (
            '{ "nested_knob": {{ .Values.service.multiTenant.enabled }},\n'
            '  "broken_knob": {{ .Values.service.multiTenant.gone }} }\n'
        ),
    }
    forged = forge(tmp_path, files, docs)
    live, _ = run_rules(
        forged, rule_names=["settings-flow"],
        allowlists={"settings-flow": frozenset()},
    )
    text = "\n".join(f.render() for f in live)
    assert "nested_knob" not in text, text
    assert "broken_knob" in text and "values.yaml" in text, text


def test_stale_baseline_entry_is_a_finding(tmp_path):
    """Stale-baseline hygiene: a suppression whose fingerprint matches
    no current finding is itself reported, so fixed violations cannot
    leave their entries rotting; a LIVE entry stays a plain
    suppression, and a --rule subset never judges entries it cannot
    see."""
    forged = forge(
        tmp_path, {"sub/x.py": "import time\nnow = time.time()\n"}
    )
    live, _ = run_rules(
        forged, rule_names=["wall-clock"],
        allowlists={"wall-clock": frozenset()},
    )
    (violation,) = live
    baseline = {
        violation.fingerprint: "known debt",
        "deadbeefdeadbeef": "fixed long ago",
    }
    # full rule set: the matching entry suppresses, the stale one fires
    live2, suppressed = run_rules(forged, baseline=baseline)
    stale = [f for f in live2 if f.rule == "stale-baseline"]
    assert len(stale) == 1
    assert "deadbeefdeadbeef" in stale[0].message
    assert "fixed long ago" in stale[0].message
    assert [s.fingerprint for s in suppressed] == [violation.fingerprint]
    # rule subset: entries owned by other rules are not judged
    live3, _ = run_rules(
        forged, rule_names=["wall-clock"],
        allowlists={"wall-clock": frozenset()}, baseline=baseline,
    )
    assert not [f for f in live3 if f.rule == "stale-baseline"]


def test_profile_timings_share_one_region_scan(tmp_path, monkeypatch):
    """--profile attribution (the PR-14 memoization note): the two lock
    rules share ONE region scan; under --profile the scan is warmed
    OUTSIDE the per-rule timers and reported as its own `shared-scan`
    line, so the scan is built exactly once and neither rule's number
    silently absorbs it."""
    from karpenter_tpu.analysis import locks as locks_mod

    builds = []
    orig_init = locks_mod._RegionScan.__init__

    def counting_init(self, *args, **kwargs):
        builds.append(1)
        orig_init(self, *args, **kwargs)

    monkeypatch.setattr(locks_mod._RegionScan, "__init__", counting_init)
    forged = forge(
        tmp_path,
        {
            "service/x.py": (
                "import threading\n"
                "class S:\n"
                "    def __init__(self):\n"
                "        self._a = threading.Lock()\n"
                "        self._b = threading.Lock()\n"
                "    def f(self):\n"
                "        with self._a:\n"
                "            with self._b:\n"
                "                pass\n"
            ),
        },
    )
    timings = {}
    run_rules(
        forged, rule_names=["lock-blocking", "lock-order"],
        allowlists={
            "lock-blocking": frozenset(), "lock-order": frozenset(),
        },
        timings=timings,
    )
    assert sum(builds) == 1, "the shared region scan built more than once"
    assert "shared-scan" in timings
    assert {"lock-blocking", "lock-order"} <= set(timings)


def test_baseline_suppresses_by_fingerprint(tmp_path):
    """A baselined fingerprint moves the finding to the suppressed list
    without deleting the signal; unrelated findings stay live."""
    forged = forge(
        tmp_path, {"x.py": "import time\na = time.time()\nb = time.sleep(1)\n"}
    )
    live, _ = run_rules(
        forged, rule_names=["wall-clock"], allowlists={"wall-clock": frozenset()}
    )
    assert len(live) == 2
    baseline = {live[0].fingerprint: "known"}
    live2, suppressed = run_rules(
        forged, rule_names=["wall-clock"],
        allowlists={"wall-clock": frozenset()}, baseline=baseline,
    )
    assert len(live2) == 1 and len(suppressed) == 1
    assert suppressed[0].fingerprint == live[0].fingerprint


# ------------------------------------------------------------ CLI smoke
def test_cli_smoke_real_package_is_clean():
    """Tier-1 smoke (the CI gate): ``python -m karpenter_tpu lint
    --json`` over the real package in a fresh process reports zero
    non-baselined findings and exits 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "karpenter_tpu", "lint", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/local/bin:/usr/bin:/bin"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["version"] == 1
    assert report["counts"]["findings"] == 0, report["findings"]
    assert report["findings"] == []
    # every registered rule ran
    assert report["rules"] == sorted(RULES)


def test_cli_exit_codes(tmp_path):
    """0 clean / 1 findings / 2 internal error, and --rule filtering."""
    from karpenter_tpu.analysis.cli import main as lint_main

    pkg = tmp_path / "forged"
    (pkg / "sub").mkdir(parents=True)
    (pkg / "sub" / "x.py").write_text("import time\nnow = time.time()\n")
    assert lint_main(["--root", str(pkg), "--rule", "wall-clock"]) == 1
    assert lint_main(["--root", str(pkg), "--rule", "store-frame"]) == 0
    # unknown rule = the checker itself is misconfigured: internal error
    assert lint_main(["--root", str(pkg), "--rule", "no-such-rule"]) == 2
