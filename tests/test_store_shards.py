"""Sharded, crash-durable store plane (docs/designs/store-scale.md,
PR 17).

Covers the plane's new legs at the unit/integration level — the full
fleet proof lives in test_sim_fleet_shards.py:

1. the durable replay log's torn-tail rule at the DISK boundary
   (truncated prefix, overrun length, zero-length and undecodable
   payloads: dropped, never IndexError'd or wrongly decoded) and its
   checkpoint+tail recovery semantics;
2. frame hardening at the SOCKET boundary — every scripted wire fault
   surfaces as a reconnect-classified error (ValueError /
   ConnectionError), and a live client HEALS each one with one retry;
3. the shard router and live key migration under the epoch fence;
4. the injectable reconnect-backoff pace seam (watchclient.py);
5. epoch rotation under rapid double-restart: two server restarts
   inside ONE client reconnect window must land the client on the
   newest epoch with a full resync, never a stale-epoch delta;
6. restart-from-disk: a durable server re-adopts its epoch and serves
   a DELTA resync across its own death.
"""

import os
import socket
import struct
import threading
import time

import pytest

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.service.codec import (
    CODEC_BIN,
    CODEC_JSON,
    decode_payload,
    encode_payload,
    recv_frame,
    send_frame,
)
from karpenter_tpu.service.shardrouter import (
    LEASE_SHARD,
    ShardCoordinator,
    ShardRouter,
    shard_of,
)
from karpenter_tpu.service.store_server import StoreServer, VersionedStore
from karpenter_tpu.service.watchclient import (
    RECONNECT_ERRORS,
    WatchChannelClient,
)
from karpenter_tpu.sim.faults import (
    WIRE_FAULTS,
    FailingFsync,
    WireFaultInjector,
)
from karpenter_tpu.state.kube import Node
from karpenter_tpu.state.remote import RemoteKubeStore
from karpenter_tpu.state.storelog import (
    FSYNC_ALWAYS,
    FSYNC_OFF,
    DurableReplayLog,
    read_segment,
)
from karpenter_tpu.utils.clock import FakeClock


def _wait(cond, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


def _record_bytes(rec: dict) -> bytes:
    payload = encode_payload(rec, CODEC_BIN)
    return struct.pack(">Q", len(payload)) + payload


# ------------------------------------------------- torn-tail rule (disk)
class TestTornTail:
    """Recovery's contract: everything before the first tear is kept,
    the tear is counted, nothing after it is trusted."""

    def _batch(self, seq, epoch="e1"):
        return {"type": "batch", "seq": seq, "epoch": epoch, "events": []}

    def test_truncated_length_prefix_dropped(self, tmp_path):
        path = tmp_path / "seg.log"
        path.write_bytes(_record_bytes(self._batch(1)) + b"\x00\x00\x01")
        records, torn = read_segment(str(path))
        assert [r["seq"] for r in records] == [1]
        assert torn == 1

    def test_declared_length_overruns_file(self, tmp_path):
        path = tmp_path / "seg.log"
        path.write_bytes(
            _record_bytes(self._batch(1))
            + struct.pack(">Q", 4096)
            + b"short"
        )
        records, torn = read_segment(str(path))
        assert [r["seq"] for r in records] == [1]
        assert torn == 1

    def test_zero_length_record_is_torn_not_indexerror(self, tmp_path):
        path = tmp_path / "seg.log"
        path.write_bytes(struct.pack(">Q", 0) + _record_bytes(self._batch(9)))
        records, torn = read_segment(str(path))
        # the zero-length payload is a tear; the valid record AFTER it is
        # garbage-by-association (a boundary found by luck is not trust)
        assert records == []
        assert torn == 1

    def test_undecodable_payload_dropped(self, tmp_path):
        path = tmp_path / "seg.log"
        path.write_bytes(
            _record_bytes(self._batch(1))
            + struct.pack(">Q", 3)
            + b"\xff\xff\xff"
        )
        records, torn = read_segment(str(path))
        assert [r["seq"] for r in records] == [1]
        assert torn == 1

    def test_missing_file_is_empty_not_error(self, tmp_path):
        assert read_segment(str(tmp_path / "absent.log")) == ([], 0)

    def test_recovery_keeps_contiguous_epoch_matched_tail(self, tmp_path):
        dlog = DurableReplayLog(str(tmp_path / "seg.log"), fsync=FSYNC_OFF)
        dlog.write_checkpoint("e1", 0, 0, 0, {}, {"kinds": {}})
        dlog.append_batch(1, "e1", [])
        dlog.append_batch(2, "e1", [])
        dlog.append_batch(3, "e2", [])  # stray other-epoch record: skipped
        dlog.append_batch(5, "e1", [])  # then a seq gap: untrusted suffix
        dlog.close()
        fresh = DurableReplayLog(str(tmp_path / "seg.log"), fsync=FSYNC_OFF)
        checkpoint, batches = fresh.recover()
        assert checkpoint["epoch"] == "e1"
        assert [b["seq"] for b in batches] == [1, 2]

    def test_checkpoint_supersedes_earlier_batches(self, tmp_path):
        reg = Registry()
        dlog = DurableReplayLog(
            str(tmp_path / "seg.log"), fsync=FSYNC_OFF, registry=reg
        )
        dlog.append_batch(1, "e1", [])
        dlog.write_checkpoint("e1", 4, 40, 0, {}, {"kinds": {}})
        dlog.append_batch(5, "e1", [])
        dlog.append_batch(6, "e1", [])
        dlog.close()
        assert reg.counter("karpenter_store_log_checkpoints_total") == 1
        fresh = DurableReplayLog(str(tmp_path / "seg.log"), fsync=FSYNC_OFF)
        checkpoint, batches = fresh.recover()
        # checkpointing atomically REPLACED the segment, so the pre-
        # checkpoint batch is gone from disk, and the tail is only what
        # follows the checkpoint's seq in its epoch
        assert checkpoint["seq"] == 4 and checkpoint["rv"] == 40
        assert [b["seq"] for b in batches] == [5, 6]

    def test_fsync_failure_fails_closed(self, tmp_path):
        reg = Registry()
        fsync = FailingFsync()
        dlog = DurableReplayLog(
            str(tmp_path / "seg.log"),
            fsync=FSYNC_ALWAYS,
            fsync_fn=fsync,
            registry=reg,
        )
        dlog.append_batch(1, "e1", [])
        fsync.arm()
        dlog.append_batch(2, "e1", [])  # fsync raises: log fails closed
        assert dlog.failed is True
        assert fsync.failures == 1
        dlog.append_batch(3, "e1", [])  # inert, no raise
        assert reg.counter("karpenter_store_log_failures_total") == 1
        # batch 2's bytes landed before the fsync raised (the OS still
        # holds them — only a POWER loss would tear them); batch 3,
        # appended after the failure, must never appear
        fresh = DurableReplayLog(str(tmp_path / "seg.log"), fsync=FSYNC_OFF)
        _cp, batches = fresh.recover()
        assert [b["seq"] for b in batches] == [1, 2]


# --------------------------------------------- frame hardening (socket)
class TestSocketFrameHardening:
    """Satellite (a): zero-length and truncated length-prefix frames
    must surface as reconnect-classified errors — ValueError or
    ConnectionError, never IndexError, never a hang."""

    def test_decode_payload_zero_length_raises_valueerror(self):
        with pytest.raises(ValueError):
            decode_payload(b"", CODEC_BIN)
        with pytest.raises(ValueError):
            decode_payload(b"", CODEC_JSON)

    def test_decode_payload_truncated_raises_valueerror(self):
        whole = encode_payload({"method": "ping"}, CODEC_BIN)
        for cut in (1, 2, len(whole) - 1):
            with pytest.raises(ValueError):
                decode_payload(whole[:cut], CODEC_BIN)
        with pytest.raises(ValueError):
            decode_payload(b"\x00\x01", CODEC_JSON)  # < 4 header bytes
        with pytest.raises(ValueError):
            # JSON header length overruns the payload
            decode_payload(struct.pack(">I", 4096) + b"{}", CODEC_JSON)

    def test_every_wire_fault_is_reconnect_classified(self):
        """Each scripted fault, fed through the REAL socket recv path,
        raises something the watch/RPC loops classify as reconnect-
        worthy — within a bounded time (no hang)."""
        for fault, response in sorted(WIRE_FAULTS.items()):
            a, b = socket.socketpair()
            try:
                a.sendall(response)
                a.close()  # then the wire dies
                b.settimeout(5.0)
                with pytest.raises(RECONNECT_ERRORS):
                    payload = recv_frame(b)
                    decode_payload(payload, CODEC_BIN)
            finally:
                b.close()

    def test_client_heals_every_fault_with_one_retry(self):
        """A poisoned RPC connection costs one retry, never a wrong
        answer: after each injected fault the next write lands."""
        srv = StoreServer().start_background()
        injector = WireFaultInjector()
        client = None
        try:
            host, port = srv.address
            client = RemoteKubeStore(
                host, port, identity="victim", start_watch=False
            )
            for i, fault in enumerate(sorted(WIRE_FAULTS)):
                injector.inject(client._channels[0], fault)
                client.put_pod(Pod(name=f"heal{i}", requests=Resources(cpu=1)))
                assert f"default/heal{i}" in srv.store.kube.pods, fault
            assert injector.injected == {f: 1 for f in WIRE_FAULTS}
        finally:
            if client is not None:
                client.close()
            srv.stop()

    def test_inject_unknown_fault_refuses(self):
        with pytest.raises(ValueError, match="unknown wire fault"):
            WireFaultInjector().inject(object(), "gremlins")

    def test_delay_ack_advances_injected_clock(self):
        srv = StoreServer().start_background()
        injector = WireFaultInjector()
        clock = FakeClock()
        client = None
        try:
            host, port = srv.address
            client = RemoteKubeStore(
                host, port, identity="slow", start_watch=False
            )
            client.put_pod(Pod(name="warm", requests=Resources(cpu=1)))
            t0 = clock.now()
            injector.delay_ack(client._channels[0], clock, 7.5)
            client.put_pod(Pod(name="delayed", requests=Resources(cpu=1)))
            # the delay burned SIMULATED time, not wall time, and the
            # response still landed
            assert clock.now() - t0 == pytest.approx(7.5)
            assert "default/delayed" in srv.store.kube.pods
        finally:
            if client is not None:
                client.close()
            srv.stop()


# ------------------------------------------------------- shard routing
class TestShardRouter:
    def test_shard_of_deterministic_and_in_range(self):
        for n in (1, 2, 4, 5):
            for i in range(50):
                s = shard_of("Pod", f"default/p{i}", n)
                assert 0 <= s < n
                assert s == shard_of("Pod", f"default/p{i}", n)

    def test_leases_pin_to_shard_zero(self):
        router = ShardRouter(4)
        for name in ("leader", "anything", "x" * 40):
            assert router.owner("Lease", name) == LEASE_SHARD

    def test_keys_spread_over_every_shard(self):
        owners = {shard_of("Pod", f"default/p{i}", 4) for i in range(200)}
        assert owners == {0, 1, 2, 3}

    def test_kind_participates_in_the_hash(self):
        placements = {
            shard_of(kind, "default/same-key", 5)
            for kind in ("Pod", "Node", "NodeClaim", "NodePool")
        }
        assert len(placements) > 1  # not all kinds co-hash


class TestMigration:
    def _server(self, index):
        return StoreServer(
            store=VersionedStore(), shard_index=index
        ).start_background()

    def test_reshard_moves_keys_under_epoch_fence(self):
        servers = [self._server(i) for i in range(2)]
        writer = None
        reg = Registry()
        try:
            addrs = [s.address for s in servers]
            writer = RemoteKubeStore(
                identity="w", shards=addrs, start_watch=False
            )
            for i in range(30):
                writer.put_pod(Pod(name=f"m{i}", requests=Resources(cpu=1)))
            pre_epochs = [s.store.epoch for s in servers]
            servers.append(self._server(2))
            new_addrs = [s.address for s in servers]
            stats = ShardCoordinator(registry=reg).reshard(addrs, new_addrs)
            assert stats["moved_keys"] > 0
            assert stats["new_n"] == 3
            # every key sits at exactly its new owner
            for i in range(30):
                key = f"default/m{i}"
                owner = shard_of("Pod", key, 3)
                for j, s in enumerate(servers):
                    assert (key in s.store.kube.pods) == (j == owner), key
            # the epoch fence: every shard the migration touched rotated,
            # so no pre-migration cursor can claim delta coverage
            for s, pre in zip(servers[:2], pre_epochs):
                if reg.counter(
                    "karpenter_store_shard_migration_begun_total",
                    {"shard": str(s.shard_index)},
                ):
                    assert s.store.epoch != pre
            # begun == committed: nothing stuck
            for s in servers[:2]:
                shard = {"shard": str(s.shard_index)}
                assert reg.counter(
                    "karpenter_store_shard_migration_begun_total", shard
                ) == reg.counter(
                    "karpenter_store_shard_migration_committed_total", shard
                )
        finally:
            if writer is not None:
                writer.close()
            for s in servers:
                s.stop()

    def test_doctor_names_a_stuck_migration(self):
        from karpenter_tpu.obs.doctor import ledger_events, suspected_causes

        ticks = [
            {"counters": {
                "karpenter_store_shard_migration_begun_total{shard=1}": 1.0,
            }},
            {"counters": {}},
        ]
        causes = suspected_causes(ticks, ledger_events(ticks), {})
        assert any("stuck in migration" in c for c in causes), causes
        # a committed migration is NOT a cause
        ticks[1]["counters"] = {
            "karpenter_store_shard_migration_committed_total{shard=1}": 1.0,
        }
        causes = suspected_causes(ticks, ledger_events(ticks), {})
        assert not any("stuck in migration" in c for c in causes), causes


# ------------------------------------------- reconnect-backoff pace seam
class TestPaceSeam:
    def test_injected_pace_sees_exponential_capped_backoff(self):
        """Satellite (b): all reconnect waiting routes through the ONE
        injectable pace callable — the injected pacer observes the
        exponential backoff schedule and can stop the loop."""
        delays = []

        def pace(delay_s):
            delays.append(delay_s)
            return len(delays) >= 6  # True stops the loop

        def dial():
            raise ConnectionError("scripted: server down")

        WatchChannelClient(
            dial=dial,
            hello=dict,
            tx=lambda sock, payload: None,
            rx=lambda sock, codec: b"",
            on_epoch=lambda epoch: None,
            on_legacy_snapshot=lambda snap: None,
            on_frame=lambda frame, initial: None,
            stop=threading.Event(),
            backoff_s=0.05,
            backoff_max=0.3,
            pace=pace,
        ).run()  # returns because pace said stop — no wall-clock sleeps
        assert delays == [0.05, 0.1, 0.2, 0.3, 0.3, 0.3]

    def test_remote_store_routes_reconnects_through_watch_pace(self):
        """RemoteKubeStore(watch_pace=...) hands the seam to its watch
        loops: with the server gone, reconnect attempts wait on the
        injected pacer instead of the wall clock."""
        srv = StoreServer().start_background()
        host, port = srv.address
        paced = threading.Event()

        def pace(_delay_s):
            paced.set()
            return False  # keep reconnecting

        client = RemoteKubeStore(
            host, port, identity="paced", watch_pace=pace
        )
        try:
            assert client.wait_synced(timeout=8.0)
            srv.stop()
            _wait(paced.is_set, msg="watch loop consulted the pace seam")
        finally:
            client.close()


# ------------------------------------- epoch rotation under double-restart
class TestDoubleRestartEpoch:
    def test_client_lands_on_newest_epoch_with_full_resync(self):
        """Satellite (c): two server restarts inside one client
        reconnect window.  The middle incarnation's epoch must never
        leak into the client's cursor — when the reconnect finally
        happens it presents a cursor the NEWEST server rejects as
        foreign-epoch and answers with a full snapshot; the client's
        mirror is the newest state, and no stale-epoch replay occurred."""
        srv1 = StoreServer().start_background()
        host, port = srv1.address
        gate = threading.Event()
        gated = threading.Event()

        def pace(_delay_s):
            gated.set()
            gate.wait(timeout=30.0)
            return False

        client = RemoteKubeStore(
            host, port, identity="survivor", watch_pace=pace
        )
        srv2 = srv3 = writer = None
        try:
            assert client.wait_synced(timeout=8.0)
            client.put_pod(Pod(name="era1", requests=Resources(cpu=1)))
            _wait(lambda: "default/era1" in client.pods, msg="era1 mirrored")
            epoch1 = srv1.store.epoch

            # restart #1: the client's watch loop hits the dead socket
            # and parks on the gated pacer — its reconnect window is OPEN
            srv1.stop()
            _wait(gated.is_set, msg="client parked in reconnect backoff")
            srv2 = StoreServer(host=host, port=port).start_background()
            writer = RemoteKubeStore(
                host, port, identity="w2", start_watch=False
            )
            writer.put_pod(Pod(name="era2", requests=Resources(cpu=1)))
            writer.close()
            writer = None
            epoch2 = srv2.store.epoch

            # restart #2, still inside the same window
            srv2.stop()
            srv3 = StoreServer(host=host, port=port).start_background()
            writer = RemoteKubeStore(
                host, port, identity="w3", start_watch=False
            )
            writer.put_pod(Pod(name="era3", requests=Resources(cpu=1)))
            epoch3 = srv3.store.epoch
            assert len({epoch1, epoch2, epoch3}) == 3

            gate.set()  # release the one reconnect
            _wait(
                lambda: client._watch_epoch == epoch3,
                msg="client adopted the newest epoch",
            )
            _wait(
                lambda: "default/era3" in client.pods
                and "default/era1" not in client.pods
                and "default/era2" not in client.pods,
                msg="mirror is the newest incarnation's state",
            )
            # the resync was a SNAPSHOT — a stale-epoch delta would have
            # registered as 'replay' (and left era1 behind)
            assert client.watch_resyncs.get("snapshot", 0) >= 1
            assert client.watch_resyncs.get("replay", 0) == 0
        finally:
            gate.set()
            client.close()
            if writer is not None:
                writer.close()
            for s in (srv1, srv2, srv3):
                if s is not None:
                    s.stop()


# --------------------------------------------- restart-from-disk (delta)
class TestDurableRestart:
    def test_restarted_server_serves_delta_from_disk(self, tmp_path):
        """The tentpole's durability claim, unit-scale: a killed store
        restarted over its segment re-adopts its epoch and serves a
        reconnecting mirror a DELTA resync — never a snapshot."""
        path = str(tmp_path / "shard-0.log")
        srv1 = StoreServer(
            store=VersionedStore(
                durable_log=DurableReplayLog(path, fsync=FSYNC_OFF)
            )
        ).start_background()
        host, port = srv1.address
        gate = threading.Event()

        def pace(_delay_s):
            gate.wait(timeout=30.0)
            return False

        client = RemoteKubeStore(
            host, port, identity="mirror", watch_pace=pace
        )
        srv2 = writer = None
        try:
            assert client.wait_synced(timeout=8.0)
            # seed through a SEPARATE writer: the fan-out skips the
            # originator, so a mirror's own writes never advance its
            # watch cursor — the delta claim needs a real cursor
            seeder = RemoteKubeStore(
                host, port, identity="seed", start_watch=False
            )
            for i in range(20):
                seeder.put_pod(Pod(name=f"pre{i}", requests=Resources(cpu=1)))
            seeder.close()
            _wait(
                lambda: client._watch_seq == srv1.store.log_seq,
                msg="watch cursor caught up",
            )
            epoch1 = srv1.store.epoch

            srv1.stop()  # crash: the client parks on the gated pacer
            srv2 = StoreServer(
                host=host,
                port=port,
                store=VersionedStore(
                    durable_log=DurableReplayLog(path, fsync=FSYNC_OFF)
                ),
            ).start_background()
            # same epoch, same seq space, state recovered from disk
            assert srv2.store.epoch == epoch1
            assert len(srv2.store.kube.pods) == 20
            writer = RemoteKubeStore(
                host, port, identity="w", start_watch=False
            )
            writer.put_pod(Pod(name="post", requests=Resources(cpu=1)))

            gate.set()
            _wait(
                lambda: "default/post" in client.pods
                and len(client.pods) == 21,
                msg="mirror resynced across the restart",
            )
            # the gap was served from the recovered disk tail: a replay,
            # not a snapshot
            assert client.watch_resyncs.get("replay", 0) >= 1
            assert client.watch_resyncs.get("snapshot", 0) == 0
        finally:
            gate.set()
            client.close()
            if writer is not None:
                writer.close()
            for s in (srv1, srv2):
                if s is not None:
                    s.stop()


# ----------------------------------------------- sharded client end-to-end
class TestShardedClient:
    def test_fanout_merge_leases_and_migrated_fencing(self):
        """The sharded RemoteKubeStore in one pass: writes fan out to
        their hash owners, N watch streams merge into one mirror, leases
        pin to shard 0, a live 4→5 reshard keeps every mirror
        consistent, and a migrated key's dirty-flush fencing survives
        because its per-key rv moved with it."""
        servers = [
            StoreServer(
                store=VersionedStore(), shard_index=i
            ).start_background()
            for i in range(4)
        ]
        a = b = None
        try:
            addrs = [s.address for s in servers]
            a = RemoteKubeStore(identity="writer", shards=addrs)
            b = RemoteKubeStore(identity="reader", shards=addrs)
            for k in range(24):
                a.put_pod(Pod(name=f"p{k}", requests=Resources(cpu=1)))
                a.put_node(Node(name=f"n{k}", capacity=Resources(cpu=8)))
            for k in range(24):
                owner = shard_of("Pod", f"default/p{k}", 4)
                assert f"default/p{k}" in servers[owner].store.kube.pods
            assert a.wait_synced(timeout=8.0)
            assert b.wait_synced(timeout=8.0)
            _wait(
                lambda: len(b.pods) == 24 and len(b.nodes) == 24,
                msg="merged mirror",
            )

            # leases pin to shard 0; CAS refuses the second holder
            assert a.try_acquire_lease(
                "leader", "writer", now=100.0, duration_s=30.0
            )
            assert "leader" in servers[0].store.kube.leases
            assert all(
                "leader" not in s.store.kube.leases for s in servers[1:]
            )
            assert not b.try_acquire_lease(
                "leader", "reader", now=101.0, duration_s=30.0
            )

            # events route by obj_name and merge into every mirror
            a.record_event("Normal", "Launched", "default/p0", "up")
            _wait(lambda: len(b.events) >= 1, msg="merged events")

            # live reshard 4 → 5 under the epoch fence
            servers.append(
                StoreServer(
                    store=VersionedStore(), shard_index=4
                ).start_background()
            )
            new_addrs = [s.address for s in servers]
            stats = ShardCoordinator().reshard(addrs, new_addrs)
            assert stats["moved_keys"] > 0
            a.apply_topology(new_addrs)
            b.apply_topology(new_addrs)
            a.put_pod(Pod(name="post-migrate", requests=Resources(cpu=1)))
            assert a.wait_synced(timeout=8.0)
            assert b.wait_synced(timeout=8.0)
            _wait(lambda: len(b.pods) == 25, msg="post-reshard mirror")

            # dirty-flush fencing at the MIGRATED owner: mutate the
            # mirror in place; the lease acquire flushes dirty state
            # with the per-key base_rv that traveled with the key
            b.pods["default/p3"].labels["smoke"] = "dirty"
            assert b.try_acquire_lease(
                "flush", "reader", now=200.0, duration_s=30.0
            )
            owner = shard_of("Pod", "default/p3", 5)
            _wait(
                lambda: servers[owner]
                .store.kube.pods["default/p3"]
                .labels.get("smoke")
                == "dirty",
                msg="dirty flush fenced at the migrated owner",
            )
        finally:
            for c in (a, b):
                if c is not None:
                    c.close()
            for s in servers:
                s.stop()
