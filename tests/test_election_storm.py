"""Election storm (VERDICT r5 item 6): two real Operators over ONE
shared StoreServer under the shuffled-controller fuzzer.

This is the proof the shared-store subsystem exists for: with durable
state in one place (service/store_server.py) and both replicas dialing
it as clients (state/remote.py), the Lease election is real — so the
single-writer invariant must hold through every failover mode:

- **crash**: the leader stops ticking and renewing; the standby takes
  over after lease expiry.
- **graceful release**: the leader frees the Lease mid-tick (the SIGTERM
  path); the standby takes over immediately.
- **renewal loss**: the leader's renewal thread is lost mid-tick and the
  clock jumps past expiry; the standby legitimately acquires, and the
  old leader must self-fence (LeaderElector.still_leading) before its
  next controller mutates anything.

Invariants asserted: every NodeClaim launch came from the replica that
held a valid lease in that round and no claim launches twice; nomination
writers never overlap in a round; and the converged end state passes the
consistency checker with kube and cloud in agreement.
"""

import random

import pytest

from karpenter_tpu.api import NodeClass, NodePool, Pod, Resources, Settings
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import SelectorTerm, tolerates_all
from karpenter_tpu.cloud.fake.backend import FakeCloud, generate_catalog
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.operator import Operator
from karpenter_tpu.service.store_server import StoreServer
from karpenter_tpu.state.kube import Node
from karpenter_tpu.state.remote import RemoteKubeStore
from karpenter_tpu.testing import FAST_BATCH_WINDOWS
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.leader import LEASE_DURATION_S, LeaderElector

N_TICKS = 46
CRASH_AT, REJOIN_AT, RELEASE_AT, RENEWAL_LOSS_AT = 10, 18, 26, 36


class StormHarness:
    """Two real Operators + electors over one StoreServer and one cloud,
    driven deterministically from the test thread (the same shape as
    tests/test_race.py's fuzzer, with the store crossing real sockets)."""

    def __init__(self):
        self.server = StoreServer().start_background()
        host, port = self.server.address
        self.clock = FakeClock()
        self.cloud = FakeCloud(
            self.clock, shapes=generate_catalog()
        ).with_default_topology()
        settings = Settings(cluster_name="storm")
        self.ops = {}
        self.kubes = {}
        self.launches = []  # (round, replica, claim_name)
        self.round_no = 0
        for name in ("replica-a", "replica-b"):
            kube = RemoteKubeStore(host, port, identity=name)
            elector = LeaderElector(kube, self.clock, name)
            op = Operator(
                self.cloud,
                kube,
                settings=settings,
                clock=self.clock,
                registry=Registry(),
                batch_windows=FAST_BATCH_WINDOWS,
                elector=elector,
            )
            self._instrument_launches(op, name)
            self.kubes[name] = kube
            self.ops[name] = op
        # cluster defaults through one client; the other sees them via
        # its watch stream
        kube = self.kubes["replica-a"]
        kube.put_node_class(
            NodeClass(
                name="default",
                subnet_selector_terms=[SelectorTerm.of(Name="*")],
                security_group_selector_terms=[SelectorTerm.of(Name="*")],
            )
        )
        kube.put_node_pool(NodePool(name="default", node_class_ref="default"))
        self.sync()

    def _instrument_launches(self, op, name):
        orig = op.cloud_provider.create

        def create(claim, _orig=orig, _name=name):
            self.launches.append((self.round_no, _name, claim.name))
            return _orig(claim)

        op.cloud_provider.create = create

    # ------------------------------------------------------------- plumbing
    def close(self):
        for kube in self.kubes.values():
            kube.close()
        self.server.stop()

    def sync(self, note: str = ""):
        for name, kube in self.kubes.items():
            if not kube.wait_synced(timeout=10.0):
                from karpenter_tpu.state.wire import STORE_KINDS, canonical

                dirty = [
                    (kind, key, kube._rvs.get((kind, key)))
                    for kind, (_c, attr, _k) in STORE_KINDS.items()
                    for key, obj in getattr(kube, attr).items()
                    if kube._shadow.get((kind, key)) != canonical(obj)
                ]
                raise AssertionError(
                    f"mirror {name} failed to sync ({note}): "
                    f"synced_rv={kube.synced_rv} "
                    f"server_rv={self.server.store.rv} dirty={dirty[:6]}"
                )

    def _controllers(self, op):
        return [
            ("nodeclass", op.node_class_controller),
            ("provisioner", op.provisioner),
            ("lifecycle", op.lifecycle),
            ("disruption", op.disruption),
            ("termination", op.termination),
            ("link", op.link),
            ("garbagecollection", op.garbage_collection),
            ("tagging", op.tagging),
        ]

    def shuffled_tick(self, name, rng, mid_tick_hook=None):
        """One elector-gated tick with shuffled controller order —
        operator.reconcile_once's gating (acquire, then a still_leading
        check before every controller) with the fuzzer's shuffle."""
        op = self.ops[name]
        if not op.elector.acquire_or_renew():
            return None
        ran = []
        seq = self._controllers(op)
        rng.shuffle(seq)
        for i, (cname, controller) in enumerate(seq):
            if not op.elector.still_leading():
                break
            controller.reconcile()
            ran.append(cname)
            if mid_tick_hook is not None:
                mid_tick_hook(i)
        return ran

    def kubelet_step(self):
        """FakeKubelet's job over the shared store: register Nodes for
        running instances, bind pods the CURRENT leader nominated."""
        kube = self.kubes["replica-a"]  # any client: writes replicate
        now = self.clock.now()
        for claim in list(kube.node_claims.values()):
            if not claim.provider_id or claim.deleted_at is not None:
                continue
            inst = self.cloud.instances.get(claim.provider_id)
            if inst is None or inst.state != "running":
                continue
            if kube.node_by_provider_id(claim.provider_id) is not None:
                continue
            labels = dict(claim.labels)
            labels[L.LABEL_HOSTNAME] = claim.name
            kube.put_node(
                Node(
                    name=claim.name,
                    provider_id=claim.provider_id,
                    labels=labels,
                    taints=list(claim.taints),
                    capacity=claim.capacity,
                    allocatable=claim.allocatable,
                    ready=True,
                    created_at=now,
                )
            )
        # leader-first nomination lookup: a deposed replica's in-memory
        # nominations are inert, exactly like a deposed process's heap
        ordered = sorted(
            self.ops.items(), key=lambda kv: not kv[1].elector.leading
        )
        for pod in list(kube.pods.values()):
            if pod.node_name or pod.phase != "Pending":
                continue
            for _name, op in ordered:
                target = op.cluster.nominated_node(pod.key())
                if target is None:
                    continue
                node = kube.nodes.get(target)
                if node is None or not node.ready or node.cordoned:
                    continue
                if not tolerates_all(pod.tolerations, node.taints):
                    continue
                kube.bind_pod(pod.key(), node.name)
                op.cluster.clear_nomination(pod.key())
                break

    def settle(self, max_rounds=40):
        for _ in range(max_rounds):
            if not self.kubes["replica-a"].pending_pods():
                break
            self.clock.step(2.0)
            self.sync()
            self.kubelet_step()
            self.sync()
            for op in self.ops.values():
                op.reconcile_once()
            self.sync()
            self.kubelet_step()
            self.sync()


@pytest.mark.parametrize("seed", [0, 1])
def test_election_storm_single_writer_invariant(seed):
    rng = random.Random(seed)
    h = StormHarness()
    try:
        live_pods = []
        crashed = set()
        round_writers = {}  # round -> [replica names that ran controllers]
        failover_rounds = set()

        for tick in range(N_TICKS):
            h.round_no = tick
            # -- workload churn (through one client; replicates to both)
            kube = h.kubes["replica-a"]
            ev = rng.random()
            if ev < 0.45:
                p = Pod(
                    requests=Resources(
                        cpu=rng.choice([0.5, 1, 2]), memory="1Gi"
                    )
                )
                kube.put_pod(p)
                live_pods.append(p)
            elif ev < 0.55 and live_pods:
                kube.delete_pod(live_pods.pop().key())
            elif ev < 0.60:
                running = [
                    i
                    for i in h.cloud.instances.values()
                    if i.state == "running"
                ]
                if running:  # out-of-band kill
                    h.cloud.terminate_instances([rng.choice(running).id])

            h.clock.step(rng.choice([0.5, 1.0, 2.0]))
            h.sync(f"tick {tick} pre-kubelet")
            h.kubelet_step()
            h.sync(f"tick {tick} post-kubelet")

            # -- forced failover modes
            if tick == CRASH_AT:
                leader = next(
                    n for n, op in h.ops.items() if op.elector.leading
                )
                crashed.add(leader)  # stops ticking AND renewing
                failover_rounds.add(tick)
            if tick == REJOIN_AT:
                crashed.clear()  # deposed replica rejoins as standby
            # expire the crashed leader's lease so the standby takes over
            if CRASH_AT <= tick < REJOIN_AT:
                h.clock.step(LEASE_DURATION_S / 3 + 1)

            writers = []
            noms_added = {}
            order = sorted(h.ops)  # a then b; shuffle the order too
            rng.shuffle(order)
            for name in order:
                if name in crashed:
                    continue
                op = h.ops[name]
                before = set(op.cluster._nominations)

                hook = None
                if tick == RELEASE_AT and op.elector.leading:
                    # graceful handoff forced mid-tick (the SIGTERM path)
                    failover_rounds.add(tick)

                    def hook(i, _op=op):
                        if i == 2:
                            _op.elector.release()

                if tick == RENEWAL_LOSS_AT and op.elector.leading:
                    # renewal thread lost: the clock jumps past expiry
                    # mid-tick and the standby immediately acquires; the
                    # old leader must run ZERO further controllers
                    failover_rounds.add(tick)
                    standby = next(n for n in h.ops if n != name)

                    def hook(i, _standby=standby):
                        if i == 2:
                            h.clock.step(LEASE_DURATION_S + 1)
                            assert h.ops[_standby].elector.acquire_or_renew()

                ran = h.shuffled_tick(name, rng, mid_tick_hook=hook)
                if ran is not None:
                    writers.append(name)
                    if tick == RENEWAL_LOSS_AT and len(ran) >= 3 and hook:
                        # self-fence: exactly the 3 pre-expiry controllers
                        assert len(ran) == 3, (
                            f"deposed leader kept reconciling: {ran}"
                        )
                added = set(op.cluster._nominations) - before
                if added:
                    noms_added[name] = added

            round_writers[tick] = writers
            # single-writer: two replicas may run in one round only
            # across an explicit failover handoff
            if len(writers) > 1:
                assert tick in failover_rounds, (tick, writers)
            # no duplicate nomination: writers in one round never
            # nominate the same pod
            if len(noms_added) > 1:
                keys = list(noms_added.values())
                assert not (keys[0] & keys[1]), noms_added

            h.sync(f"tick {tick} post-ticks")
            h.kubelet_step()
            h.sync(f"tick {tick} final")

        # -- every launch came from a replica that was a writer that round
        for rnd, name, claim in h.launches:
            assert name in round_writers.get(rnd, ()), (rnd, name, claim)
        # -- no NodeClaim double-launch, across every failover
        names = [c for _, _, c in h.launches]
        assert len(names) == len(set(names)), (
            f"double-launched claims: {[c for c in names if names.count(c) > 1]}"
        )
        assert len({n for _, n, _ in h.launches}) == 2, (
            "failovers should have made BOTH replicas lead at some point"
        )

        # -- convergence: settle under the final leader, then check the
        # usual kube<->cloud agreement and the consistency oracle
        h.settle()
        for _ in range(3):
            h.clock.step(35.0)
            h.sync()
            h.kubelet_step()
            for op in h.ops.values():
                op.reconcile_once()
            h.sync()
            h.kubelet_step()
            h.sync()
        h.settle(max_rounds=20)
        kube = h.kubes["replica-a"]
        assert not kube.pending_pods()
        live_claims = {
            c.provider_id
            for c in kube.node_claims.values()
            if c.deleted_at is None and c.provider_id
        }
        running = {
            i.id for i in h.cloud.instances.values() if i.state == "running"
        }
        assert live_claims <= running
        # the consistency checker is the invariant oracle
        from karpenter_tpu.controllers.consistency import CHECK_PERIOD

        leader = next(
            (op for op in h.ops.values() if op.elector.leading), None
        )
        assert leader is not None, "storm ended with no leader"
        h.clock.step(CHECK_PERIOD + 1)
        leader.consistency.reconcile()
        h.sync()
        violations = [
            e for e in kube.events if e[1] == "ConsistencyViolation"
        ]
        assert not violations, violations
    finally:
        h.close()


def test_failover_preserves_scheduled_state():
    """The regression the shared store exists to prevent: after a leader
    crash, the NEW leader sees the old leader's claims through the store
    and does NOT re-launch capacity for pods that are already placed."""
    h = StormHarness()
    try:
        rng = random.Random(7)
        kube_a = h.kubes["replica-a"]
        for _ in range(6):
            kube_a.put_pod(Pod(requests=Resources(cpu=1, memory="2Gi")))
        # A leads and provisions; kubelet registers + binds
        assert h.shuffled_tick("replica-a", rng) is not None
        for _ in range(10):
            h.clock.step(2.0)
            h.sync()
            h.kubelet_step()
            h.sync()
            h.shuffled_tick("replica-a", rng)
            if not kube_a.pending_pods():
                break
        h.sync()
        h.kubelet_step()
        h.sync()
        assert not kube_a.pending_pods()
        launched_before = len(h.launches)
        claims_before = set(kube_a.node_claims)
        # A crashes; the lease expires; B takes over with a warm mirror
        h.clock.step(LEASE_DURATION_S + 1)
        assert h.shuffled_tick("replica-b", rng) is not None
        assert h.ops["replica-b"].elector.leading
        for _ in range(4):
            h.clock.step(2.0)
            h.sync()
            h.kubelet_step()
            h.sync()
            h.shuffled_tick("replica-b", rng)
        # B saw the placed pods + claims via the store: nothing re-launched
        assert len(h.launches) == launched_before, h.launches[launched_before:]
        kube_b = h.kubes["replica-b"]
        assert set(kube_b.node_claims) == claims_before
    finally:
        h.close()
