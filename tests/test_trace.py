"""Span tracing / profiling (reference --enable-profiling + pprof,
website v0.31 settings.md:18): the tracer must be free when off, nest
correctly, aggregate per path, and wire through operator + solver."""

import json
import threading

from karpenter_tpu.api import Pod, Resources, Settings
from karpenter_tpu.testing import Environment
from karpenter_tpu.utils.trace import TRACER, Tracer, device_trace


class TestTracer:
    def test_disabled_records_nothing(self):
        t = Tracer(enabled=False)
        with t.span("a"):
            pass
        assert t.stats() == {}
        assert t.recent() == []

    def test_nested_paths_and_aggregates(self):
        t = Tracer(enabled=True)
        for _ in range(3):
            with t.span("tick"):
                with t.span("inner"):
                    pass
        st = t.stats()
        assert st["tick"].count == 3
        assert st["tick.inner"].count == 3
        assert st["tick"].total_s >= st["tick.inner"].total_s
        assert st["tick"].max_s > 0

    def test_span_records_meta_and_survives_exception(self):
        t = Tracer(enabled=True)
        try:
            with t.span("boom", pods=7):
                raise RuntimeError("x")
        except RuntimeError:
            pass
        (span,) = t.recent()
        assert span.path == "boom" and span.meta == {"pods": "7"}
        # the stack unwound: the next span is top-level again
        with t.span("after"):
            pass
        assert t.recent()[-1].path == "after"

    def test_threads_have_independent_stacks(self):
        t = Tracer(enabled=True)
        done = threading.Event()

        def worker():
            with t.span("w"):
                done.wait(1.0)

        th = threading.Thread(target=worker)
        with t.span("main"):
            th.start()
            with t.span("inner"):
                pass
        done.set()
        th.join()
        paths = {s.path for s in t.recent()}
        assert "w" in paths  # not "main.w": thread-local stacks
        assert "main.inner" in paths

    def test_report_and_dump(self, tmp_path):
        t = Tracer(enabled=True)
        with t.span("x"):
            pass
        rep = t.report()
        assert "x" in rep and "count" in rep
        out = tmp_path / "spans.json"
        t.dump(str(out))
        payload = json.loads(out.read_text())
        assert payload["stats"]["x"]["count"] == 1
        assert payload["recent"][0]["path"] == "x"

    def test_device_trace_noop_when_disabled(self):
        t = Tracer(enabled=False)
        with device_trace(t, "/nonexistent/dir"):
            pass  # must not touch jax.profiler at all


class TestWiring:
    def test_operator_spans_controllers_and_solver(self):
        env = Environment(
            settings=Settings(cluster_name="test", enable_profiling=True)
        )
        TRACER.reset()
        try:
            env.default_node_class()
            env.default_node_pool()
            env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="1Gi")))
            env.settle()
            st = env.operator.tracer.stats()
            assert st["controller.provisioner"].count > 0
            assert st["controller.disruption"].count > 0
            # solver phases nested under the provisioner tick
            solver_spans = [k for k in st if "solver.compile" in k]
            assert solver_spans, sorted(st)
            assert any("solver.fetch" in k for k in st)
            assert any("solver.decode" in k for k in st)
        finally:
            TRACER.enabled = False
            TRACER.profile_dir = ""
            TRACER.reset()

    def test_profiling_off_by_default(self):
        env = Environment()
        assert env.operator.tracer.enabled is False


class TestPhaseCollector:
    def test_noop_without_sink(self):
        from karpenter_tpu.utils.trace import phase

        with phase("anything"):
            pass  # must not raise, must not record anywhere

    def test_self_time_is_disjoint(self):
        import time

        from karpenter_tpu.utils.trace import phase, phase_collect

        sink = {}
        with phase_collect(sink):
            with phase("outer"):
                time.sleep(0.01)
                with phase("inner"):
                    time.sleep(0.01)
        assert set(sink) == {"outer", "inner"}
        # inner's time is SUBTRACTED from outer (self-time accounting),
        # so the buckets are disjoint and sum to the wall clock
        assert sink["inner"] >= 0.009
        assert sink["outer"] >= 0.009
        assert sink["outer"] < 0.02 + 0.005

    def test_accumulates_across_repeated_phases(self):
        from karpenter_tpu.utils.trace import phase, phase_collect

        sink = {}
        with phase_collect(sink):
            for _ in range(3):
                with phase("step"):
                    pass
        assert len(sink) == 1 and sink["step"] >= 0.0

    def test_sink_restored_after_block(self):
        from karpenter_tpu.utils.trace import phase, phase_collect

        outer_sink, inner_sink = {}, {}
        with phase_collect(outer_sink):
            with phase_collect(inner_sink):
                with phase("a"):
                    pass
            with phase("b"):
                pass
        assert "a" in inner_sink and "a" not in outer_sink
        assert "b" in outer_sink and "b" not in inner_sink

    def test_thread_local_sinks(self):
        from karpenter_tpu.utils.trace import phase, phase_collect

        sink = {}
        seen = {}

        def worker():
            # no sink installed on THIS thread: phase is a no-op
            with phase("worker-phase"):
                seen["ran"] = True

        with phase_collect(sink):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["ran"]
        assert "worker-phase" not in sink
