"""Bench-harness contract tests (tier-1).

The smoke test drives bench.py's REAL emit path — every config builder,
every per-line assert, the same JSON schema — at tiny scale, so schema
regressions (a line missing its `phases`, a negative `device_ms`, the
flagship not printing last) fail in CI instead of in the next round's
BENCH artifact.
"""

import io
import json
import contextlib

import pytest

import bench


REQUIRED_FIELDS = {"metric", "value", "unit", "vs_baseline", "path", "kernel", "nodes"}
PHASE_NAMES = {
    "partition", "compile", "pad", "dispatch", "device_block",
    "oracle", "decode", "other", "harness", "delta",
    # the load-harness line's sim-tick phase split (bench.run_load_harness)
    "generate", "apply", "reconcile", "invariants",
}


@pytest.fixture(scope="module")
def bench_lines():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main(tiny=True)
    return [json.loads(line) for line in buf.getvalue().strip().splitlines()]


class TestBenchSmoke:
    def test_every_line_well_formed(self, bench_lines):
        assert bench_lines
        for line in bench_lines:
            assert REQUIRED_FIELDS <= set(line), line
            assert line["unit"] == "ms"
            assert line["value"] > 0
            assert line["vs_baseline"] > 0

    def test_every_line_carries_phases(self, bench_lines):
        for line in bench_lines:
            phases = line.get("phases")
            assert isinstance(phases, dict) and phases, line["metric"]
            assert set(phases) <= PHASE_NAMES, (line["metric"], phases)
            assert all(v >= 0.0 for v in phases.values()), line
            # disjoint self-time spans + the harness residual sum to ~ the
            # reported p50 (rounding of each span is the only slack)
            total = sum(phases.values())
            assert total == pytest.approx(
                line["value"], abs=0.01 * len(phases) + 0.05
            ), (line["metric"], total, line["value"])

    def test_device_ms_nonnegative(self, bench_lines):
        for line in bench_lines:
            if "device_ms" in line:
                assert line["device_ms"] >= 0.0, line
            if "device_ms_floor" in line:
                assert line["device_ms_floor"] >= 0.0, line

    def test_flagship_prints_last(self, bench_lines):
        assert bench_lines[-1]["metric"] == "schedule_10k_pods_500_types_p50"

    def test_scheduler_lines_carry_cold_and_warm(self, bench_lines):
        """Every solve-style line reports the cold (first solve: full
        tensorize + upload) vs resident-warm split, so the resident win
        is visible in the artifact and --compare can gate warm_ms."""
        for line in bench_lines:
            if not line["metric"].startswith(
                ("schedule_", "consolidation_")
            ):
                continue
            assert line.get("cold_ms", 0) > 0, line["metric"]
            assert line.get("warm_ms", 0) > 0, line["metric"]
            # warm == the reported p50 by construction
            assert line["warm_ms"] == pytest.approx(line["value"])

    def test_resident_100k_line(self, bench_lines):
        """The sharded-resident scale line: served from the resident
        buffers (hits > 0) with the one cold rebuild."""
        line = next(
            l
            for l in bench_lines
            if l["metric"] == "schedule_100k_pods_1k_nodes_resident_p50"
        )
        assert line["path"] == "tensor"
        assert line["resident_hits"] > 0
        assert line["resident_rebuilds"] >= 1
        # the warm tick skips pad/upload entirely: no pad phase
        assert "pad" not in line["phases"], line["phases"]

    def test_consolidation_sweep_line(self, bench_lines):
        """The batched-vs-sequential sweep line carries both measurements
        (the speedup is measured in-bench, not asserted) plus the batch
        size of the final dispatch."""
        line = next(
            l
            for l in bench_lines
            if l["metric"] == "consolidation_sweep_60_candidates_p50"
        )
        assert line["path"] in ("batched", "sequential")
        assert line["sequential_ms"] > 0
        assert line["batch"] >= 0
        assert line["speedup_vs_sequential"] > 0

    def test_consolidation_search_line(self, bench_lines):
        """The population-search line carries its search shape (rounds /
        population = distinct subsets scored) next to the sequential
        measurement over the SAME coverage — the 10x acceptance floor is
        asserted on the full-scale artifact, measured here."""
        line = next(
            l
            for l in bench_lines
            if l["metric"] == "consolidation_search_500_candidates_p50"
        )
        assert line["path"] in ("batched", "sequential")
        assert line["rounds"] >= 1
        assert line["population"] >= 2
        assert line["sequential_ms"] > 0
        assert line["speedup_vs_sequential"] > 0

    def test_pipelined_tick_line(self, bench_lines):
        """The pipelined-reconcile line drives the REAL operator through
        the diurnal+interruption-storm schedule twice (sequential vs
        pipelined) and reports both p50s; the speed floors (adoption,
        realized overlap, pipelined <= sequential) assert on the
        full-scale artifact inside run_pipelined_tick itself — at tiny
        scale the handful of ticks is structure-only."""
        line = next(
            l
            for l in bench_lines
            if l["metric"] == "reconcile_tick_pipelined_p50"
        )
        assert line["path"] == "pipelined"
        assert line["sequential_ms"] > 0
        assert line["pipelined_ms"] > 0
        assert line["pipelined_ms"] == pytest.approx(line["value"], abs=0.01)
        assert line["speedup"] > 0
        assert line["speculations_adopted"] >= 0
        assert line["overlap_seconds"] >= 0
        assert line["max_phase_ms"] >= 0
        assert line["ticks"] >= 3

    def test_load_harness_line(self, bench_lines):
        """The million-event load-harness line: a columnar tape drives
        the full operator with the vectorized invariant plane on, and the
        harness's own share of the tick wall (generate + invariants) is
        reported next to the throughput.  The 1M-event and <20% floors
        assert on the full-scale artifact inside run_load_harness — at
        tiny scale this checks structure and that the fraction is sane."""
        line = next(
            l for l in bench_lines if l["metric"] == "load_harness_1m_events"
        )
        assert line["path"] == "load" and line["kernel"] == "tape"
        assert line["events_total"] > 0
        assert line["events_per_sec"] > 0
        assert line["vector_checked_ticks"] > 0
        assert line["ticks"] >= 12
        assert 0.0 <= line["harness_fraction"] < 1.0
        assert line["harness_ms"] >= 0
        # the phase split is the sim's, not the solver's
        assert {"generate", "reconcile", "invariants"} <= set(line["phases"])

    def test_store_ops_line(self, bench_lines):
        """The fleet-scale store plane's throughput line: the negotiated
        binary codec must carry >= 3x the tagged-JSON baseline on the
        same server-side op mix (the acceptance floor), and fewer bytes
        per op."""
        line = next(
            l for l in bench_lines if l["metric"] == "store_ops_mixed_p50"
        )
        assert line["kernel"] == "bin1"
        assert line["subscribers"] >= 8
        assert line["ops_per_sec_bin1"] > 0 and line["ops_per_sec_json"] > 0
        assert line["speedup_codec"] >= 3.0, line
        assert line["bytes_per_op_bin1"] < line["bytes_per_op_json"]

    def test_store_resync_line(self, bench_lines):
        """The delta watch resync line: a 10-event gap must replay (not
        snapshot) and move < 10% of the full-snapshot bytes (the
        acceptance floor)."""
        line = next(
            l
            for l in bench_lines
            if l["metric"] == "store_watch_resync_p50"
        )
        assert line["kind"] == "replay"
        assert line["gap_events"] == 10
        assert 0 < line["delta_bytes"] < line["snapshot_bytes"]
        assert line["bytes_ratio"] < 0.10, line

    def test_solver_service_line(self, bench_lines):
        """The multi-tenant service line: a barrier-released 16-tenant
        burst must actually coalesce into fleet dispatches
        (batched_solves > 0) and the measured window must compile
        nothing — the cold window enumerates the whole padded-bucket
        ladder.  The 2x aggregation floor asserts inside bench.py at
        full scale only (tiny problems can't amortize a dispatch)."""
        line = next(
            l
            for l in bench_lines
            if l["metric"] == "solver_service_16_tenants_agg"
        )
        assert line["path"] == "batched" and line["kernel"] == "fleet"
        assert line["tenants"] == 16
        assert line["batched_solves"] > 0, line
        assert line["cold_ms"] > 0
        assert line["sequential_ms"] > 0
        assert line["solves_per_sec_service"] > 0
        assert line["speedup_vs_sidecars"] > 0
        assert line["compile_count_warm"] == 0, line

    def test_solve_lines_carry_device_counters(self, bench_lines):
        """Every solve-style line reports the device observatory's cold
        vs warm split: compile counts and transfer bytes for the first
        solve + warmups vs the measured window."""
        for line in bench_lines:
            if not line["metric"].startswith(
                ("schedule_", "consolidation_")
            ):
                continue
            for f in (
                "compile_count_cold", "compile_count_warm",
                "transfer_bytes_cold", "transfer_bytes_warm",
            ):
                assert f in line, (line["metric"], f)
                assert line[f] >= 0, (line["metric"], f, line[f])
        # somewhere the cold windows did real device work (all-zero
        # columns would mean the seams came unwired); honest zeros exist
        # — the repack line's scheduler is settle-warmed before the
        # window opens, and the sidecar's transfers belong to the remote
        # process
        assert any(
            line.get("transfer_bytes_cold", 0) > 0 for line in bench_lines
        )
        assert any(
            line.get("compile_count_cold", 0) > 0 for line in bench_lines
        )

    def test_flagship_warm_window_compiles_nothing(self, bench_lines):
        """Acceptance: the flagship warm line shows compile_count == 0
        (every measured solve replays cached programs) and its transfer
        bytes bounded by the delta — an unchanged cluster re-serving the
        resident snapshot ships NOTHING."""
        line = bench_lines[-1]
        assert line["metric"] == "schedule_10k_pods_500_types_p50"
        assert line["compile_count_warm"] == 0, line
        assert line["transfer_bytes_warm"] == 0, line

    def test_scale_restored_after_tiny_run(self, bench_lines):
        assert bench.SCALE == 1.0 and bench.ITERS == 21


class TestCompare:
    def test_compare_lines_flags_regression_over_threshold(self):
        old = [{"metric": "a_p50", "value": 100.0},
               {"metric": "b_p50", "value": 100.0},
               {"metric": "gone_p50", "value": 5.0}]
        new = [{"metric": "a_p50", "value": 126.0},   # +26%: regressed
               {"metric": "b_p50", "value": 124.0},   # +24%: within noise
               {"metric": "fresh_p50", "value": 9.0}]  # new line: reported
        rows, regressed = bench.compare_lines(new, old)
        assert regressed == ["a_p50"]
        text = "\n".join(rows)
        assert "REGRESSION" in text
        assert "(new line)" in text and "(absent from this run)" in text

    def test_load_bench_lines_driver_artifact_and_jsonl(self, tmp_path):
        """Both prior-file shapes parse: the driver's BENCH_rNN.json
        wrapper ({"tail": jsonl-with-noise}) and a raw JSONL dump."""
        lines = [{"metric": "x_p50", "value": 10.0, "unit": "ms"}]
        raw = "\n".join(json.dumps(l) for l in lines)
        wrapper = tmp_path / "BENCH_r99.json"
        wrapper.write_text(json.dumps(
            {"n": 99, "cmd": "python bench.py", "rc": 0,
             "tail": "some log noise\n" + raw + "\n"}
        ))
        assert bench._load_bench_lines(str(wrapper)) == lines
        jsonl = tmp_path / "prior.jsonl"
        jsonl.write_text(raw + "\n")
        assert bench._load_bench_lines(str(jsonl)) == lines

    def test_tiny_compare_run_exits_clean_without_regression(
        self, bench_lines, tmp_path, capsys
    ):
        """Drive the REAL --compare path at tiny scale: a prior file with
        generously slower values (so measurement noise cannot fake a
        regression) compares clean, prints per-line deltas, returns 0 —
        and --compare-out writes the machine-readable verdict with the
        schema CI and `doctor --bench` ingest."""
        prior = tmp_path / "prior.jsonl"
        prior.write_text(
            "\n".join(
                json.dumps(
                    {
                        **l,
                        "value": l["value"] * 100.0,
                        **(
                            {"warm_ms": l["warm_ms"] * 100.0}
                            if "warm_ms" in l
                            else {}
                        ),
                    }
                )
                for l in bench_lines
            )
            + "\n"
        )
        out = tmp_path / "verdict.json"
        rc = bench.main(tiny=True, compare=str(prior), compare_out=str(out))
        captured = capsys.readouterr()
        assert rc == 0
        assert "REGRESSION" not in captured.err
        assert "->" in captured.err  # per-line p50 deltas printed
        # stdout stayed the machine-readable line stream
        for line in captured.out.strip().splitlines():
            assert "metric" in json.loads(line)
        # the verdict schema (the --compare-out satellite)
        verdict = json.loads(out.read_text())
        assert verdict["baseline"] == str(prior)
        assert verdict["ok"] is True and verdict["regressed"] == []
        assert verdict["threshold"] == bench.COMPARE_THRESHOLD
        assert len(verdict["lines"]) >= len(bench_lines)
        for line in verdict["lines"]:
            assert {"metric", "prior_ms", "new_ms", "delta_pct",
                    "regressed", "status"} <= set(line)
            assert line["status"] in ("compared", "new", "absent")
            assert line["regressed"] is False
        # the lint verdict rides the same artifact (static-analysis
        # satellite): clean package, all rules, rendered in the table
        lint = verdict["lint"]
        assert lint["ok"] is True and lint["findings"] == 0
        assert lint["rules"] >= 15 and lint["details"] == []
        assert "lint" in captured.err and "clean" in captured.err
        # ...and the SANITIZER verdict rides next to it (runtime half of
        # the lock plane): scripted sanitized store scenario, zero
        # findings, witness cross-validated against the static model
        san = verdict["sanitizer"]
        assert san["ok"] is True and san["findings"] == 0
        assert san["cross_validation_ok"] is True
        assert san["missing_static"] == 0
        assert len(san["witness_fingerprint"]) == 16
        assert "sanitizer" in captured.err

    def test_compare_verdict_flags_regressions(self):
        old = [{"metric": "a_p50", "value": 100.0}]
        new = [{"metric": "a_p50", "value": 130.0},
               {"metric": "b_p50", "value": 5.0}]
        verdict = bench.compare_verdict(new, old)
        assert verdict["ok"] is False
        assert verdict["regressed"] == ["a_p50"]
        by_metric = {l["metric"]: l for l in verdict["lines"]}
        assert by_metric["a_p50"]["delta_pct"] == pytest.approx(30.0)
        assert by_metric["a_p50"]["regressed"] is True
        assert by_metric["b_p50"]["status"] == "new"


class TestMarginalEstimate:
    def test_clamps_negative_estimate_at_measurement_site(self):
        # chained runs FASTER than chain x single (noise-inflated
        # baseline): the raw difference is negative, the site clamps —
        # this is the r05 `device_ms: -1.4` regression pinned
        t1s = [0.110, 0.105, 0.108]
        tks = [0.100, 0.102, 0.101]
        est, floor = bench._marginal_estimate(t1s, tks, chain=6)
        assert est == 0.0
        assert floor >= 0.0

    def test_positive_estimate_passes_through(self):
        t1s = [0.100, 0.101, 0.102]
        tks = [0.150, 0.152, 0.151]
        est, floor = bench._marginal_estimate(t1s, tks, chain=6)
        assert est == pytest.approx((0.150 - 0.100) / 5 * 1000.0)
        assert floor >= 0.0

    def test_emit_refuses_negative_device_ms(self, capsys):
        with pytest.raises(ValueError, match="negative device_ms"):
            bench._emit("m", 10.0, "tensor", "scan", 1, device_ms=-1.4)
        assert capsys.readouterr().out == ""

    def test_emit_refuses_negative_zero_device_ms(self, capsys):
        """The site the original clamp missed: a tiny negative estimate
        rounds to -0.0, which compares == 0 and slipped the `v < 0`
        guard — it must refuse like any other negative."""
        with pytest.raises(ValueError, match="negative device_ms"):
            bench._emit(
                "m", 10.0, "tensor", "scan", 1, device_ms=round(-0.004, 2)
            )
        with pytest.raises(ValueError, match="negative device_ms"):
            bench._emit(
                "m", 10.0, "tensor", "scan", 1,
                device_ms=0.5, device_ms_floor=-0.0,
            )
        assert capsys.readouterr().out == ""

    def test_compare_flags_malformed_prior_without_gating(self):
        """The r05 regression pinned on the ingest side: a prior artifact
        carrying device_ms -1.4 is flagged malformed in the verdict and
        the rendered table, but does NOT fail the comparison (history is
        immutable; BENCH_r05.json must stay comparable)."""
        old = [{"metric": "pallas_p50", "value": 100.0, "device_ms": -1.4}]
        new = [{"metric": "pallas_p50", "value": 90.0, "device_ms": 0.7}]
        verdict = bench.compare_verdict(new, old)
        assert verdict["ok"] is True
        assert verdict["malformed"] == {"new": [], "prior": ["pallas_p50"]}
        text = "\n".join(bench.render_verdict(verdict))
        assert "MALFORMED prior line" in text

    def test_compare_flags_malformed_new_lines(self):
        new = [{"metric": "x_p50", "value": 10.0, "device_ms": -0.0}]
        verdict = bench.compare_verdict(new, [])
        assert verdict["malformed"]["new"] == ["x_p50"]

    def test_warm_regression_gates_like_p50(self):
        """A line whose p50 held steady but whose resident-warm solve
        regressed past the threshold fails the comparison; warm fields
        absent on either side never gate (pre-resident baselines)."""
        old = [{"metric": "a_p50", "value": 100.0, "warm_ms": 10.0},
               {"metric": "b_p50", "value": 100.0}]
        new = [{"metric": "a_p50", "value": 101.0, "warm_ms": 14.0},
               {"metric": "b_p50", "value": 101.0, "warm_ms": 50.0}]
        verdict = bench.compare_verdict(new, old)
        assert verdict["regressed"] == ["a_p50"]
        by = {l["metric"]: l for l in verdict["lines"]}
        assert by["a_p50"]["warm_delta_pct"] == pytest.approx(40.0)
        assert "warm_delta_pct" not in by["b_p50"]

    def test_silent_recompile_gates_even_when_p50_got_lucky(self):
        """The device-observatory gate: a line whose warm window went
        from compiling nothing to compiling SOMETHING regresses — even
        with a faster p50.  Absent counters (pre-observatory baselines)
        never gate; a warm count that was already nonzero does not gate
        on staying nonzero."""
        old = [
            {"metric": "a_p50", "value": 100.0, "compile_count_warm": 0},
            {"metric": "b_p50", "value": 100.0},
            {"metric": "c_p50", "value": 100.0, "compile_count_warm": 2},
        ]
        new = [
            {"metric": "a_p50", "value": 80.0, "compile_count_warm": 3},
            {"metric": "b_p50", "value": 99.0, "compile_count_warm": 1},
            {"metric": "c_p50", "value": 99.0, "compile_count_warm": 2},
        ]
        verdict = bench.compare_verdict(new, old)
        assert verdict["ok"] is False
        assert verdict["regressed"] == ["a_p50"]
        by = {l["metric"]: l for l in verdict["lines"]}
        assert by["a_p50"]["new_compile_count_warm"] == 3
        assert "new_compile_count_warm" not in by["b_p50"]
        text = "\n".join(bench.render_verdict(verdict))
        assert "warm recompiles 0 -> 3" in text
