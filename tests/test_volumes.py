"""Persistent volume topology (reference website v0.31
concepts/scheduling.md:387-411): StorageClass allowed topologies +
volumeBindingMode constrain where a consuming pod's node may land, and the
first consumer anchors WaitForFirstConsumer volumes."""

import pytest

from karpenter_tpu.api import (
    Disruption,
    PersistentVolumeClaim,
    Pod,
    Resources,
    StorageClass,
)
from karpenter_tpu.api import labels as L
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    e = Environment()
    e.default_node_class()
    e.default_node_pool()
    return e


def _node_zone(env, pod_key):
    node_name = env.kube.pods[pod_key].node_name
    return env.kube.nodes[node_name].labels[L.LABEL_ZONE]


class TestVolumeTopology:
    def test_bound_claim_pins_zone(self, env):
        env.kube.put_storage_class(
            StorageClass(name="zonal", zones=("zone-b",), binding_mode="Immediate")
        )
        env.kube.put_pvc(
            PersistentVolumeClaim(name="data", storage_class="zonal")
        )
        assert env.kube.pvcs["default/data"].bound_zone == "zone-b"
        pod = Pod(requests=Resources(cpu=1, memory="2Gi"), volume_claims=["data"])
        env.kube.put_pod(pod)
        env.settle()
        assert not env.kube.pending_pods()
        assert _node_zone(env, pod.key()) == "zone-b"

    def test_wffc_allowed_topologies_then_anchor(self, env):
        """An unbound WaitForFirstConsumer claim admits the storage class's
        zones; the first consumer's zone anchors it for later consumers."""
        env.kube.put_storage_class(
            StorageClass(name="zonal", zones=("zone-a", "zone-c"))
        )
        env.kube.put_pvc(
            PersistentVolumeClaim(name="shared", storage_class="zonal")
        )
        first = Pod(requests=Resources(cpu=1, memory="2Gi"), volume_claims=["shared"])
        env.kube.put_pod(first)
        env.settle()
        assert not env.kube.pending_pods()
        zone1 = _node_zone(env, first.key())
        assert zone1 in ("zone-a", "zone-c")
        assert env.kube.pvcs["default/shared"].bound_zone == zone1

        # a second consumer must follow the volume — force the solve to
        # want elsewhere by filling nothing; just assert the zone matches
        second = Pod(
            requests=Resources(cpu=1, memory="2Gi"), volume_claims=["shared"]
        )
        env.kube.put_pod(second)
        env.settle()
        assert not env.kube.pending_pods()
        assert _node_zone(env, second.key()) == zone1

    def test_conflicting_claims_unschedulable(self, env):
        env.kube.put_storage_class(
            StorageClass(name="a-only", zones=("zone-a",), binding_mode="Immediate")
        )
        env.kube.put_storage_class(
            StorageClass(name="b-only", zones=("zone-b",), binding_mode="Immediate")
        )
        env.kube.put_pvc(PersistentVolumeClaim(name="va", storage_class="a-only"))
        env.kube.put_pvc(PersistentVolumeClaim(name="vb", storage_class="b-only"))
        pod = Pod(
            requests=Resources(cpu=1, memory="2Gi"), volume_claims=["va", "vb"]
        )
        env.kube.put_pod(pod)
        env.settle(max_rounds=8)
        assert pod.key() in {p.key() for p in env.kube.pending_pods()}

    def test_unconstrained_storage_schedules_anywhere(self, env):
        env.kube.put_storage_class(StorageClass(name="any"))
        env.kube.put_pvc(PersistentVolumeClaim(name="v", storage_class="any"))
        pod = Pod(requests=Resources(cpu=1, memory="2Gi"), volume_claims=["v"])
        env.kube.put_pod(pod)
        env.settle()
        assert not env.kube.pending_pods()
        # the claim anchors to wherever the pod landed
        assert env.kube.pvcs["default/v"].bound_zone == _node_zone(env, pod.key())

    def test_consolidation_respects_bound_volume(self, env):
        """A repack must keep volume consumers in the volume's zone."""
        from karpenter_tpu.api import Requirement, Requirements
        from karpenter_tpu.api.requirements import Op

        env.kube.put_storage_class(
            StorageClass(name="zonal", zones=("zone-c",), binding_mode="Immediate")
        )
        env.kube.put_pvc(PersistentVolumeClaim(name="v", storage_class="zonal"))
        pool = env.kube.node_pools["default"]
        pool.disruption = Disruption(consolidation_policy="WhenUnderutilized")
        pods = [Pod(requests=Resources(cpu=2, memory="4Gi")) for _ in range(12)]
        pods.append(
            Pod(requests=Resources(cpu=1, memory="2Gi"), volume_claims=["v"])
        )
        for p in pods:
            env.kube.put_pod(p)
        env.settle(max_rounds=40)
        assert not env.kube.pending_pods()
        assert _node_zone(env, pods[-1].key()) == "zone-c"
        # shrink: delete most pods, let consolidation repack
        for p in pods[:10]:
            env.kube.delete_pod(p.key())
        for _ in range(30):
            env.clock.step(65)
            env.step(2.0)
        env.settle(max_rounds=20)
        assert not env.kube.pending_pods()
        assert _node_zone(env, pods[-1].key()) == "zone-c"


class TestStorageClassValidation:
    def test_invalid_binding_mode_rejected(self, env):
        from karpenter_tpu.api.validation import ValidationError

        with pytest.raises(ValidationError):
            env.kube.put_storage_class(
                StorageClass(name="bad", binding_mode="Sometimes")
            )
