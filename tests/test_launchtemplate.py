"""Launch-template machinery + fleet tagging tests.

Mirrors reference pkg/providers/launchtemplate/suite_test.go behaviors:
cloud-side template store, cache hydration on start, eviction deleting the
remote template, static-name passthrough (launchtemplate.go:99-145,323-357),
and the stale-template retry (instance.go:94-98).  Plus the merged-fleet
tagging contract: claim-specific tags must land only on the claim's own
instance.
"""

from concurrent.futures import ThreadPoolExecutor

import pytest

from karpenter_tpu.api import NodeClaim, Requirements, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.cloud.fake.backend import FakeLaunchTemplate
from karpenter_tpu.providers.launchtemplate import OPTIONS_HASH_TAG
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    return Environment()


@pytest.fixture()
def setup(env):
    pool = env.default_node_pool()
    nc = env.default_node_class()
    return pool, nc


def make_claim(pool, **kw):
    return NodeClaim(
        pool_name=pool.name,
        node_class_ref=pool.node_class_ref,
        requirements=Requirements(),
        requests=kw.pop("requests", Resources(cpu=1, memory="1Gi")),
        **kw,
    )


class TestLaunchTemplateStore:
    def test_launch_creates_remote_template(self, env, setup):
        pool, nc = setup
        out = env.cloud_provider.create(make_claim(pool))
        inst = env.cloud.instances[out.provider_id]
        assert inst.launch_template
        assert inst.launch_template in env.cloud.launch_templates
        lt = env.cloud.launch_templates[inst.launch_template]
        assert OPTIONS_HASH_TAG in lt.tags

    def test_repeat_launch_reuses_template(self, env, setup):
        pool, nc = setup
        env.cloud_provider.create(make_claim(pool))
        n = env.cloud.recorder.count("CreateLaunchTemplate")
        env.cloud_provider.create(make_claim(pool))
        assert env.cloud.recorder.count("CreateLaunchTemplate") == n

    def test_hydration_on_start(self, env, setup):
        """A fresh provider over a cloud that already holds this cluster's
        templates must adopt them instead of recreating
        (launchtemplate.go:323-339)."""
        pool, nc = setup
        env.cloud_provider.create(make_claim(pool))
        n_templates = len(env.cloud.launch_templates)
        n_creates = env.cloud.recorder.count("CreateLaunchTemplate")
        from karpenter_tpu.providers.launchtemplate import LaunchTemplateProvider

        fresh = LaunchTemplateProvider(
            env.cloud,
            env.launch_templates.resolver,
            env.security_groups,
            env.clock,
            cluster_name=env.launch_templates.cluster_name,
        )
        types = env.instance_types.list(pool, nc)[:5]
        out = fresh.ensure_all(nc, pool, types)
        assert out and all(t.name in env.cloud.launch_templates for t in out)
        assert len(env.cloud.launch_templates) == n_templates
        assert env.cloud.recorder.count("CreateLaunchTemplate") == n_creates

    def test_cache_eviction_deletes_remote(self, env, setup):
        pool, nc = setup
        env.cloud_provider.create(make_claim(pool))
        assert env.cloud.launch_templates
        env.clock.step(3600)  # well past the cache TTL
        env.launch_templates._cache.purge_expired()
        assert not env.cloud.launch_templates

    def test_invalidate_deletes_remote(self, env, setup):
        pool, nc = setup
        env.cloud_provider.create(make_claim(pool))
        env.launch_templates.invalidate()
        assert not env.cloud.launch_templates

    def test_static_template_passthrough(self, env, setup):
        """spec.launchTemplateName bypasses resolution entirely
        (launchtemplate.go:104-107)."""
        pool, nc = setup
        env.cloud.create_launch_template(
            FakeLaunchTemplate(
                name="user-owned",
                image_id="image-standard-amd64",
                security_group_ids=["sg-default"],
                user_data="#custom",
            )
        )
        nc.launch_template_name = "user-owned"
        n = env.cloud.recorder.count("CreateLaunchTemplate")
        out = env.cloud_provider.create(make_claim(pool))
        inst = env.cloud.instances[out.provider_id]
        assert inst.launch_template == "user-owned"
        assert inst.image_id == "image-standard-amd64"
        # no karpenter-managed template was created for this launch
        assert env.cloud.recorder.count("CreateLaunchTemplate") == n

    def test_stale_template_retried_once(self, env, setup):
        """A template deleted out-of-band between resolution and CreateFleet
        triggers exactly one invalidate-and-retry (instance.go:94-98)."""
        pool, nc = setup
        # seed the provider cache with a template, then delete it remotely
        env.cloud_provider.create(make_claim(pool))
        stale = list(env.cloud.launch_templates)
        for name in stale:
            # bypass the API so the provider cache still references it
            env.cloud.launch_templates.pop(name)
        out = env.cloud_provider.create(make_claim(pool))
        assert out.provider_id
        inst = env.cloud.instances[out.provider_id]
        assert inst.launch_template in env.cloud.launch_templates


class TestFleetTagging:
    def test_merged_batch_tags_each_claim_distinctly(self, env, setup):
        """Coalesced CreateFleet launches must each carry their own claim's
        Name/nodeclaim tags — the shared fleet request holds only pool-level
        tags (the reference merges only fully-identical CreateFleetInputs)."""
        pool, nc = setup
        # the FAST_BATCH_WINDOWS idle window is 2ms; on a loaded machine
        # a burst can miss coalescing entirely, so retry the burst — the
        # tagging assertion below needs a batch that actually merged
        for attempt in range(3):
            before = env.cloud.recorder.count("CreateFleet")
            claims = [make_claim(pool) for _ in range(6)]
            with ThreadPoolExecutor(max_workers=6) as ex:
                outs = list(ex.map(env.cloud_provider.create, claims))
            if env.cloud.recorder.count("CreateFleet") - before < 6:
                break  # coalescing actually happened
        else:
            raise AssertionError("CreateFleet never coalesced in 3 bursts")
        for claim, out in zip(claims, outs):
            inst = env.cloud.instances[out.provider_id]
            assert inst.tags["karpenter.sh/nodeclaim"] == claim.name
            assert inst.tags["Name"] == claim.name
            assert inst.tags["karpenter.sh/nodepool"] == pool.name
            assert inst.tags[L.ANNOTATION_MANAGED_BY] == "karpenter-tpu"


class TestProvisionerErrorIsolation:
    def test_generic_launch_error_does_not_kill_batch(self, env, setup):
        """One claim failing with a non-capacity error must not stop the
        other claims from launching or crash the reconcile loop."""
        pool, nc = setup
        from karpenter_tpu.api import Pod
        from karpenter_tpu.cloud.fake.backend import CloudAPIError

        for i in range(4):
            env.kube.put_pod(Pod(requests=Resources(cpu=2, memory="4Gi")))
        env.cloud.recorder.set_next_error(
            "CreateFleet", CloudAPIError("InternalError", "flaky")
        )
        env.settle()
        # the loop survived; pods eventually scheduled on retry batches
        assert not env.kube.pending_pods()
        assert env.registry.counter(
            "karpenter_nodeclaims_launch_failed", {"reason": "error"}
        ) >= 0  # no crash is the real assertion


class TestHydrationOwnership:
    def test_no_cluster_name_adopts_nothing(self, env, setup):
        """With no cluster name there is no safe ownership claim: hydration
        must not adopt (and later remote-delete) other clusters' templates
        (eviction deletes remote, launchtemplate.go:340-357)."""
        pool, nc = setup
        env.cloud.create_launch_template(
            FakeLaunchTemplate(
                name="other-cluster-lt",
                image_id="image-standard-amd64",
                security_group_ids=["sg-default"],
                user_data="#other",
                tags={
                    "karpenter.sh/cluster": "other",
                    OPTIONS_HASH_TAG: "deadbeef0000",
                },
            )
        )
        from karpenter_tpu.providers.launchtemplate import LaunchTemplateProvider

        anon = LaunchTemplateProvider(
            env.cloud,
            env.launch_templates.resolver,
            env.security_groups,
            env.clock,
            cluster_name="",
        )
        assert len(anon._cache) == 0
        env.clock.step(3600)
        anon._cache.purge_expired()
        assert "other-cluster-lt" in env.cloud.launch_templates

    def test_exact_cluster_match_only(self, env, setup):
        """Hydration adopts templates of THIS cluster only — a foreign
        cluster's template must survive this provider's cache lifecycle."""
        pool, nc = setup
        env.cloud_provider.create(make_claim(pool))  # our own template
        env.cloud.create_launch_template(
            FakeLaunchTemplate(
                name="other-cluster-lt",
                image_id="image-standard-amd64",
                security_group_ids=["sg-default"],
                user_data="#other",
                tags={
                    "karpenter.sh/cluster": "other",
                    OPTIONS_HASH_TAG: "deadbeef0000",
                },
            )
        )
        from karpenter_tpu.providers.launchtemplate import LaunchTemplateProvider

        fresh = LaunchTemplateProvider(
            env.cloud,
            env.launch_templates.resolver,
            env.security_groups,
            env.clock,
            cluster_name=env.launch_templates.cluster_name,
        )
        assert len(fresh._cache) >= 1
        env.clock.step(3600)
        fresh._cache.purge_expired()
        # ours evicted+deleted; the foreign one untouched
        assert "other-cluster-lt" in env.cloud.launch_templates


class TestScopedInvalidation:
    def test_invalidate_template_leaves_others(self, env, setup):
        """The stale-template retry must drop only the template observed
        missing; other cached templates (other node classes' in-flight
        launches) keep their entries."""
        pool, nc = setup
        env.cloud_provider.create(make_claim(pool))
        first = set(env.cloud.launch_templates)
        # second node class with different user data -> a second template
        from karpenter_tpu.api import NodeClass
        from karpenter_tpu.api.objects import SelectorTerm

        nc2 = NodeClass(
            name="alt",
            subnet_selector_terms=[SelectorTerm.of(Name="*")],
            security_group_selector_terms=[SelectorTerm.of(Name="*")],
            user_data="#alternate",
        )
        env.kube.put_node_class(nc2)
        pool2 = env.default_node_pool(name="alt")
        pool2.node_class_ref = "alt"
        claim2 = make_claim(pool2)
        claim2.node_class_ref = "alt"
        env.cloud_provider.create(claim2)
        second = set(env.cloud.launch_templates) - first
        assert second, "expected a distinct second template"
        # the stale template vanished out-of-band (that's why the retry
        # path calls invalidate_template in the first place)
        stale = next(iter(second))
        env.cloud.launch_templates.pop(stale)
        cached_before = set(env.launch_templates._cache.keys())
        env.launch_templates.invalidate_template(stale)
        # exactly one cache entry dropped; no remote deletes issued (the
        # remote template is already gone — and a concurrent retry may
        # have just recreated the same name)
        assert len(cached_before) - len(set(env.launch_templates._cache.keys())) == 1
        assert env.cloud.recorder.count("DeleteLaunchTemplate") == 0
        # every other remote template (first launch's and the rest of the
        # second's) is untouched
        assert (first | second) - {stale} <= set(env.cloud.launch_templates)
