"""Runtime concurrency sanitizer suite (analysis/sanitizer.py +
analysis/witness.py, docs/designs/static-analysis.md §runtime sanitizer).

Four layers, mirroring how test_lint.py gates the static plane:

1. **Forged-violation teeth**: a scripted two-thread lock inversion, a
   blocking op under a non-sanctioned lock, and an unprotected shared
   write must each produce EXACTLY the expected finding — and the
   matching clean scripts produce zero.  "The detector actually fires"
   is the property everything else leans on.
2. **Witness artifact**: two runs of the same seeded scenario serialize
   to identical bytes (the Findings-style determinism contract), and
   the artifact round-trips.
3. **Static<->dynamic cross-validation**: a witnessed edge the static
   model predicts is confirmed; a fabricated runtime-only edge between
   in-layer locks becomes a ``witness-gap`` finding (static-model
   incompleteness); out-of-layer edges stay informational.  The
   ``Batcher._lock -> _Bucket._cv`` case is pinned: the runtime witness
   caught that hole and the constructor-local type inference in
   locks.py now closes it.
4. **Sanitized smoke of the threaded suites** (the tier-1 CI surface):
   a short FakeCloud API-storm hammer, a store-plane writer storm with
   a live subscriber, an election mini-storm, and a pipelined operator
   run all complete under the wrappers with zero findings, and their
   merged witness shows no runtime edge missing from the static model.
"""

from __future__ import annotations

import threading

import pytest

from karpenter_tpu.analysis import sanitizer
from karpenter_tpu.analysis.allowlists import WITNESS_EDGES
from karpenter_tpu.analysis.witness import Witness, cross_validate


@pytest.fixture(autouse=True)
def _sanitizer_isolated():
    """Every test starts and ends with no active sanitizer — a leaked
    enable would silently wrap every later test's locks."""
    sanitizer.disable()
    yield
    sanitizer.disable()


def run_thread(fn, name):
    t = threading.Thread(target=fn, name=name)
    t.start()
    t.join()


# --------------------------------------------------------------- teeth
class TestForgedViolations:
    def test_scripted_lock_inversion_fires_exactly_once(self):
        san = sanitizer.enable("forged-inversion")
        a = sanitizer.make_lock("Forged._a")
        b = sanitizer.make_lock("Forged._b")

        def forward():
            with a:
                with b:
                    pass

        def backward():
            with b:
                with a:
                    pass

        run_thread(forward, "fwd")
        run_thread(backward, "bwd")
        findings = san.findings()
        assert len(findings) == 1, [f.render() for f in findings]
        (f,) = findings
        assert f.rule == "rt-lock-order"
        assert "Forged._a -> Forged._b" in f.message
        assert "Forged._b -> Forged._a" in f.message

    def test_consistent_order_produces_zero_findings(self):
        san = sanitizer.enable("forged-clean")
        a = sanitizer.make_lock("Forged._a")
        b = sanitizer.make_lock("Forged._b")

        def nested():
            with a:
                with b:
                    pass

        run_thread(nested, "t1")
        run_thread(nested, "t2")
        assert san.findings() == []
        # ...but the edge IS witnessed
        assert san.witness().edge_pairs() == {("Forged._a", "Forged._b")}

    def test_blocking_under_lock_fires(self):
        san = sanitizer.enable("forged-blocking")
        lock = sanitizer.make_lock("Forged._lock")
        with lock:
            sanitizer.note_blocking("send_frame")
        findings = san.findings()
        assert len(findings) == 1
        assert findings[0].rule == "rt-lock-blocking"
        assert "send_frame" in findings[0].message
        assert "Forged._lock" in findings[0].message

    def test_blocking_under_sanctioned_lock_is_silent(self):
        """The one-in-flight-RPC pattern: the lock EXISTS to serialize
        the blocking op (allowlists.SANITIZER_BLOCKING_LOCKS)."""
        san = sanitizer.enable("forged-sanctioned")
        lock = sanitizer.make_lock("RemoteKubeStore._rpc_lock")
        with lock:
            sanitizer.note_blocking("send_frame")
        assert san.findings() == []
        # still witnessed, marked allowed — the artifact keeps the signal
        (obs,) = san.witness().blocking
        assert obs["allowed"] is True

    def test_sanctioned_lock_does_not_launder_an_unsanctioned_one(self):
        """all-held-locks semantics: holding a one-in-flight RPC lock
        (sanctioned) must not mask the unsanctioned outer lock also
        held across the blocking op."""
        san = sanitizer.enable("forged-launder")
        outer = sanitizer.make_lock("Forged._outer")
        rpc = sanitizer.make_lock("RemoteKubeStore._rpc_lock")
        with outer:
            with rpc:
                sanitizer.note_blocking("send_frame")
        findings = san.findings()
        assert len(findings) == 1
        assert "Forged._outer" in findings[0].message

    def test_foreign_release_is_a_loud_anomaly(self):
        """A lock released by a thread that never acquired it (legal
        ownership handoff for threading.Lock) must surface as a finding
        instead of silently corrupting the witness's holder table."""
        san = sanitizer.enable("forged-handoff")
        lock = sanitizer.make_lock("Forged._lock")
        lock.acquire()

        def releaser():
            lock.release()

        run_thread(releaser, "releaser")
        findings = san.findings()
        assert len(findings) == 1
        assert findings[0].rule == "rt-foreign-release"
        assert "Forged._lock" in findings[0].message

    def test_blocking_with_no_lock_held_is_free(self):
        san = sanitizer.enable("forged-free")
        sanitizer.note_blocking("send_frame")
        assert san.findings() == []
        assert san.witness().blocking == []

    def test_unprotected_shared_write_fires_exactly_once(self):
        san = sanitizer.enable("forged-race")

        def touch():
            sanitizer.note_access("Forged.shared")

        run_thread(touch, "w1")
        run_thread(touch, "w2")
        run_thread(touch, "w3")  # more touches: still ONE finding
        findings = san.findings()
        assert len(findings) == 1
        assert findings[0].rule == "rt-race"
        assert "Forged.shared" in findings[0].message

    def test_lock_protected_shared_write_is_silent(self):
        san = sanitizer.enable("forged-protected")
        lock = sanitizer.make_lock("Forged._lock")

        def touch():
            with lock:
                sanitizer.note_access("Forged.shared")

        run_thread(touch, "w1")
        run_thread(touch, "w2")
        assert san.findings() == []
        (field,) = san.witness().fields
        assert field["state"] == "shared-modified"
        assert field["lockset"] == ["Forged._lock"]

    def test_single_thread_unprotected_writes_are_the_init_pattern(self):
        """Eraser's exclusive state: one thread initializing without
        locks is normal object construction, not a race."""
        san = sanitizer.enable("forged-init")
        for _ in range(5):
            sanitizer.note_access("Forged.shared")
        assert san.findings() == []

    def test_read_only_sharing_is_not_a_race(self):
        san = sanitizer.enable("forged-readonly")
        sanitizer.note_access("Forged.shared")  # writer initializes

        def read():
            sanitizer.note_access("Forged.shared", write=False)

        run_thread(read, "r1")
        run_thread(read, "r2")
        assert san.findings() == []
        (field,) = san.witness().fields
        assert field["state"] == "shared"

    def test_rlock_reentrancy_records_no_self_edges(self):
        san = sanitizer.enable("forged-reentrant")
        lock = sanitizer.make_rlock("Forged._rlock")
        with lock:
            with lock:
                with lock:
                    pass
        assert san.findings() == []
        assert san.witness().edges == []

    def test_condition_aliases_onto_its_wrapped_lock(self):
        """make_condition over a sanitized lock IS that lock for the
        witness (the _Subscriber.cond == VersionedStore.lock
        relationship), and wait() releases the hold — a waiter is not a
        holder."""
        san = sanitizer.enable("forged-cond")
        lock = sanitizer.make_rlock("Forged.lock")
        cond = sanitizer.make_condition("Forged.cond", lock)
        other = sanitizer.make_lock("Forged._other")
        done = threading.Event()

        def waiter():
            with cond:
                cond.wait(timeout=0.01)  # releases the hold while waiting
            done.set()

        run_thread(waiter, "waiter")
        assert done.is_set()
        with other:
            with lock:
                pass
        # the only witnessed lock names are the ALIASED pair + other:
        # "Forged.cond" never appears as its own lock
        assert "Forged.cond" not in san.witness().locks
        assert san.findings() == []


# ------------------------------------------------------------- artifact
class TestWitnessArtifact:
    def _scripted_run(self):
        san = sanitizer.enable("seeded-scenario")
        a = sanitizer.make_lock("Forged._a")
        b = sanitizer.make_lock("Forged._b")

        def forward():
            with a:
                with b:
                    sanitizer.note_access("Forged.slots")

        def backward():
            with b:
                with a:
                    sanitizer.note_blocking("send_frame")

        run_thread(forward, "t1")
        run_thread(backward, "t2")
        witness = san.witness()
        sanitizer.disable()
        return witness

    def test_same_seeded_scenario_serializes_identically(self):
        w1 = self._scripted_run()
        w2 = self._scripted_run()
        assert w1.dumps() == w2.dumps()
        assert w1.fingerprint == w2.fingerprint

    def test_round_trip_preserves_everything(self, tmp_path):
        w = self._scripted_run()
        path = tmp_path / "witness.json"
        w.dump(path)
        loaded = Witness.load(path)
        assert loaded.to_dict() == w.to_dict()
        assert loaded.fingerprint == w.fingerprint
        # findings ride the artifact (the inversion forged above)
        assert any(
            f["rule"] == "rt-lock-order" for f in loaded.findings
        )

    def test_unknown_version_refuses(self):
        with pytest.raises(ValueError, match="version"):
            Witness.from_dict({"version": 99})


# ----------------------------------------------------- cross-validation
@pytest.fixture(scope="module")
def static_model():
    from karpenter_tpu.analysis.core import PackageSnapshot
    from karpenter_tpu.analysis.locks import static_order_edges

    snap = PackageSnapshot.load()
    edges, universe = static_order_edges(snap)
    return snap, edges, universe


class TestCrossValidation:
    def test_witnessed_batcher_edge_is_confirmed(self, static_model):
        """The edge the runtime witness originally caught MISSING from
        the static model (bucket.add resolves through a stoplisted
        generic name) — pinned as confirmed now that the region scan
        does constructor-local type inference."""
        _snap, edges, universe = static_model
        assert ("Batcher._lock", "_Bucket._cv") in edges
        san = sanitizer.enable("batcher")
        from karpenter_tpu.batcher.core import Batcher

        b = Batcher(
            executor=lambda reqs: [r * 2 for r in reqs],
            idle_s=0.002, max_s=0.05, name="sanitized",
        )
        assert b.call(21) == 42
        sanitizer.disable()
        cv = cross_validate(san.witness(), edges, universe, WITNESS_EDGES)
        assert "Batcher._lock|_Bucket._cv" in cv.confirmed
        assert cv.ok, cv.missing_static

    def test_fabricated_runtime_edge_is_a_missing_static_finding(
        self, static_model
    ):
        _snap, edges, universe = static_model
        w = Witness(
            scenario="fabricated",
            locks=["StoreChannel._lock", "VersionedStore.lock"],
            edges=[{
                "outer": "StoreChannel._lock",
                "inner": "VersionedStore.lock",
                "sites": ["karpenter_tpu/state/remote.py:_rpc"],
            }],
        )
        assert ("StoreChannel._lock", "VersionedStore.lock") \
            not in edges
        cv = cross_validate(w, edges, universe, WITNESS_EDGES)
        assert not cv.ok
        assert len(cv.missing_static) == 1
        # ...and the allowlist silences it (the sanctioned-edge path)
        cv2 = cross_validate(
            w, edges, universe,
            {"StoreChannel._lock|VersionedStore.lock"},
        )
        assert cv2.ok

    def test_out_of_layer_edge_is_informational(self, static_model):
        """Registry._lock lives in metrics/ — outside LOCK_ORDER_LAYERS
        — so an edge into it is unmodeled, never a finding (the static
        rule deliberately scopes it out)."""
        _snap, edges, universe = static_model
        w = Witness(
            scenario="unmodeled",
            edges=[{
                "outer": "VersionedStore.lock",
                "inner": "Registry._lock",
                "sites": ["karpenter_tpu/service/store_server.py:_commit"],
            }],
        )
        cv = cross_validate(w, edges, universe, WITNESS_EDGES)
        assert cv.ok
        assert len(cv.unmodeled) == 1

    def test_unexercised_static_edges_are_coverage_gaps(
        self, static_model
    ):
        _snap, edges, universe = static_model
        cv = cross_validate(
            Witness(scenario="empty"), edges, universe, WITNESS_EDGES
        )
        assert cv.ok  # an empty witness proves nothing — and fails nothing
        assert len(cv.unexercised_static) == len(edges)

    def test_cli_witness_flag(self, tmp_path):
        """``lint --witness`` merges the artifact: a clean witness keeps
        exit 0 and reports the section; a fabricated runtime-only edge
        exits 1 with a witness-gap finding."""
        import json

        from karpenter_tpu.analysis.cli import main as lint_main

        clean = Witness(scenario="clean")
        p_clean = tmp_path / "clean.json"
        p_clean.write_text(clean.dumps())
        assert lint_main(
            ["--rule", "lock-order", "--witness", str(p_clean)]
        ) == 0

        bad = Witness(
            scenario="gap",
            edges=[{
                "outer": "StoreChannel._lock",
                "inner": "VersionedStore.lock",
                "sites": ["karpenter_tpu/state/remote.py:_rpc"],
            }],
        )
        p_bad = tmp_path / "gap.json"
        p_bad.write_text(bad.dumps())
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = lint_main(
                ["--rule", "lock-order", "--json",
                 "--witness", str(p_bad)]
            )
        assert rc == 1
        report = json.loads(buf.getvalue())
        assert report["witness"]["cross_validation"]["ok"] is False
        assert any(
            f["rule"] == "witness-gap" for f in report["findings"]
        )


# ------------------------------------------------- sanitized smoke (CI)
class TestSanitizedSuites:
    """Short sanitized versions of the threaded suites' shapes — the
    tier-1 smoke the CI satellite wires.  Each must complete with ZERO
    sanitizer findings, and the witnesses must cross-validate clean
    against the static model (no runtime edge the analyzer never
    predicted)."""

    def _assert_clean(self, san, static_model):
        findings = san.findings()
        assert findings == [], "\n".join(f.render() for f in findings)
        _snap, edges, universe = static_model
        cv = cross_validate(san.witness(), edges, universe, WITNESS_EDGES)
        assert cv.ok, cv.missing_static

    def test_sanitized_api_storm_hammer(self, static_model):
        """The test_race.py FakeCloud hammer shape, shortened, under the
        wrappers."""
        import random

        san = sanitizer.enable("api-storm")
        from karpenter_tpu.api.objects import SelectorTerm
        from karpenter_tpu.cloud.fake.backend import (
            FakeCloud,
            FakeLaunchTemplate,
            generate_catalog,
        )
        from karpenter_tpu.utils.clock import FakeClock

        cloud = FakeCloud(
            FakeClock(), shapes=generate_catalog()[:10]
        ).with_default_topology()
        term = [SelectorTerm.of(Name="*")]

        def attack(i):
            rng = random.Random(i)
            for n in range(40):
                op = rng.randrange(5)
                if op == 0:
                    cloud.create_launch_template(
                        FakeLaunchTemplate(name=f"lt-{i}-{n % 4}")
                    )
                elif op == 1:
                    cloud.describe_launch_templates()
                    cloud.describe_subnets(term)
                elif op == 2:
                    insts, _ = cloud.create_fleet(
                        overrides=[{
                            "instance_type":
                                cloud.describe_instance_types()[0].name,
                            "zone": "zone-a",
                            "subnet_id": "subnet-0",
                        }],
                        capacity_type="on-demand",
                    )
                    if insts and rng.random() < 0.5:
                        cloud.terminate_instances([insts[0].id])
                elif op == 3:
                    cloud.describe_instances()
                else:
                    cloud.get_products()

        threads = [
            threading.Thread(target=attack, args=(i,), name=f"storm-{i}")
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert cloud.recorder.count("CreateFleet") > 0
        sanitizer.disable()
        self._assert_clean(san, static_model)

    def test_sanitized_store_plane_writer_storm(self, static_model):
        """The store-fleet-chaos shape in miniature: two writer threads
        mutating one VersionedStore with a live subscriber draining
        (offer under the store lock, the sender's drain under the
        aliased condition)."""
        san = sanitizer.enable("store-plane")
        from karpenter_tpu.api import Pod, Resources
        from karpenter_tpu.service.store_server import VersionedStore

        store = VersionedStore()
        with store.lock:
            _mode, _payload, sub = store.subscribe("smoke-sub", "json", 0)
        drained = []
        stop = threading.Event()

        def sender():
            while True:
                with sub.cond:
                    while not (sub.batches or sub.closed):
                        if stop.is_set():
                            return
                        sub.cond.wait(0.01)
                    if sub.closed:
                        return
                    batches = list(sub.batches)
                    sub.batches.clear()
                drained.extend(b.seq for b in batches)

        def writer(tag):
            for i in range(24):
                store.mutate(
                    lambda i=i: store.kube.put_pod(
                        Pod(
                            name=f"{tag}-{i}",
                            requests=Resources(cpu=0.1, memory="1Gi"),
                        )
                    )
                )

        snd = threading.Thread(target=sender, name="sender")
        snd.start()
        ws = [
            threading.Thread(target=writer, args=(t,), name=f"writer-{t}")
            for t in ("a", "b")
        ]
        for t in ws:
            t.start()
        for t in ws:
            t.join()
        stop.set()
        snd.join()
        with store.lock:
            store.unsubscribe(sub)
        assert len(store.kube.pods) == 48
        sanitizer.disable()
        self._assert_clean(san, static_model)
        # the lockset witness saw the subscriber queue from BOTH sides
        # with the store lock as the common lockset
        fields = {f["field"]: f for f in san.witness().fields}
        batches = fields["_Subscriber.batches"]
        assert batches["state"] == "shared-modified"
        assert batches["lockset"] == ["VersionedStore.lock"]

    def test_sanitized_election_mini_storm(self, static_model):
        """The election-storm shape: competing electors CAS-ing one
        lease map from threads."""
        san = sanitizer.enable("election")
        from karpenter_tpu.state.kube import KubeStore
        from karpenter_tpu.utils.clock import FakeClock
        from karpenter_tpu.utils.leader import LeaderElector

        kube = KubeStore()
        clock = FakeClock()
        electors = [
            LeaderElector(kube, clock, identity=f"replica-{i}")
            for i in range(3)
        ]

        def contend(e):
            for _ in range(30):
                e.acquire_or_renew()

        threads = [
            threading.Thread(
                target=contend, args=(e,), name=e.identity
            )
            for e in electors
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # single-writer invariant held: exactly one holder
        assert sum(1 for e in electors if e.leading) == 1
        sanitizer.disable()
        self._assert_clean(san, static_model)
        fields = {f["field"]: f for f in san.witness().fields}
        assert fields["KubeStore.leases"]["lockset"] == [
            "KubeStore._lease_lock"
        ]

    def test_sanitized_pipeline_twin_smoke(self, static_model):
        """One pipelined operator run under the wrappers (the
        test_pipeline twin shape, shortened): the speculative stages,
        launch fan-out, and observatory seams all execute sanitized
        with zero findings."""
        san = sanitizer.enable("pipeline-twin")
        from karpenter_tpu.api import Pod, Resources, Settings
        from karpenter_tpu.testing import Environment

        env = Environment(
            settings=Settings(
                cluster_name="sanitized",
                enable_pipelined_reconcile=True,
            )
        )
        env.default_node_class()
        env.default_node_pool()
        for i in range(4):
            env.kube.put_pod(
                Pod(
                    name=f"pod-{i}",
                    requests=Resources(cpu=0.25, memory="1Gi"),
                )
            )
        for _ in range(6):
            env.step(1.0)
        assert not env.kube.pending_pods()
        sanitizer.disable()
        self._assert_clean(san, static_model)


# ------------------------------------------------------------- watchdog
class TestWatchdog:
    def test_fires_when_every_holder_stalls(self):
        san = sanitizer.enable("watchdog")
        lock = sanitizer.make_lock("Forged._lock")
        reports = []
        dog = sanitizer.LockWatchdog(
            san, stall_s=5.0, on_stall=reports.append
        )
        import time as _time

        entered = threading.Event()
        release = threading.Event()

        def holder():
            with lock:
                entered.set()
                release.wait(5.0)

        t = threading.Thread(target=holder, name="holder")
        t.start()
        entered.wait(2.0)
        now = _time.monotonic()
        assert dog.check(now=now) is None  # just acquired: not stalled
        report = dog.check(now=now + 10.0)  # every holder past the bound
        assert report is not None
        assert report["holds"][0]["lock"] == "Forged._lock"
        assert report["holds"][0]["thread"] == "holder"
        # same episode: reported once, not per poll
        assert dog.check(now=now + 11.0) is None
        assert reports == [report]
        release.set()
        t.join()
        assert dog.check(now=now + 20.0) is None  # nothing held

    def test_one_fresh_holder_disarms(self):
        """A long critical section among HEALTHY ones is not a
        deadlock: the watchdog fires only when EVERY holder stalls."""
        san = sanitizer.enable("watchdog-partial")
        import time as _time

        # simulate holds directly: one ancient, one fresh
        now = _time.monotonic()
        with san._mu:
            san._holds[(1, "Forged._a")] = ("t1", now - 100.0)
            san._holds[(2, "Forged._b")] = ("t2", now)
        dog = sanitizer.LockWatchdog(
            san, stall_s=5.0, on_stall=lambda r: None
        )
        assert dog.check(now=now) is None

    def test_watchdog_needs_sanitizer_setting(self):
        from karpenter_tpu.api import Settings

        with pytest.raises(ValueError, match="enable_lock_sanitizer"):
            Settings(
                cluster_name="x", lock_watchdog_stall_s=5.0
            ).validate()
        Settings(
            cluster_name="x",
            enable_lock_sanitizer=True,
            lock_watchdog_stall_s=5.0,
        ).validate()


# -------------------------------------------------- production plumbing
class TestOperatorWiring:
    def test_operator_arms_watchdog_only_with_sanitizer(self):
        from karpenter_tpu.api import Settings
        from karpenter_tpu.testing import Environment

        # sanitizer off: no watchdog even with the stall bound set...
        env = Environment(settings=Settings(cluster_name="t"))
        assert env.operator.watchdog is None
        # ...sanitizer on + stall bound: armed
        sanitizer.enable("operator-wiring")
        try:
            env2 = Environment(
                settings=Settings(
                    cluster_name="t",
                    enable_lock_sanitizer=True,
                    lock_watchdog_stall_s=30.0,
                )
            )
            assert env2.operator.watchdog is not None
            assert env2.operator.watchdog.stall_s == 30.0
        finally:
            sanitizer.disable()

    def test_disabled_seam_returns_stdlib_objects(self):
        """Production default: the seam hands out the stdlib classes
        themselves — zero wrapper overhead exists to measure."""
        assert sanitizer.current() is None
        lock = sanitizer.make_lock("X._lock")
        assert type(lock) is type(threading.Lock())
        rlock = sanitizer.make_rlock("X._rlock")
        assert type(rlock) is type(threading.RLock())
        cond = sanitizer.make_condition("X._cv")
        assert isinstance(cond, threading.Condition)

    def test_enable_twice_refuses(self):
        sanitizer.enable("first")
        try:
            with pytest.raises(RuntimeError, match="already enabled"):
                sanitizer.enable("second")
        finally:
            sanitizer.disable()
