"""Consolidation replacement pre-spin tests (round-2 VERDICT item #3).

The reference launches the replacement node and waits for it to be ready
before terminating the candidate (designs/consolidation.md:5-21,
website v0.31 deprovisioning.md:83-110).  These specs assert the no-gap
ordering — candidate deletion only starts after the replacement is up —
and rollback when the replacement never registers.
"""

import pytest

from karpenter_tpu.api import Disruption, Pod, Requirement, Requirements, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    return Environment()


def _replace_scenario(env):
    """One lightly-loaded on-demand node whose pods fit a strictly cheaper
    replacement: the single-node replace case."""
    env.default_node_class()
    env.default_node_pool(
        requirements=Requirements(
            [
                Requirement(
                    L.LABEL_CAPACITY_TYPE, Op.IN, [L.CAPACITY_TYPE_ON_DEMAND]
                ),
                # big nodes only, so the initial fleet overshoots
                Requirement(L.LABEL_INSTANCE_CPU, Op.GT, ["31"]),
            ]
        ),
        disruption=Disruption(consolidation_policy="WhenUnderutilized"),
    )
    pods = [
        Pod(requests=Resources(cpu=4, memory="8Gi")) for _ in range(16)
    ]
    for p in pods:
        env.kube.put_pod(p)
    env.settle()
    assert not env.kube.pending_pods()
    # shrink the workload to 2 small pods -> one big node is now oversized
    for p in pods[2:]:
        env.kube.delete_pod(p.key())
    # relax the pool so a small replacement is allowed
    pool = env.kube.node_pools["default"]
    pool.requirements = Requirements(
        [Requirement(L.LABEL_CAPACITY_TYPE, Op.IN, [L.CAPACITY_TYPE_ON_DEMAND])]
    )
    return pods[:2]


class TestReplacementPreSpin:
    def test_candidate_survives_until_replacement_ready(self, env):
        """While the replacement registers, the candidate must stay alive
        (capacity never dips); pods land on the replacement afterwards."""
        kept = _replace_scenario(env)
        before = set(env.kube.node_claims)
        env.kubelet.startup_delay = 6.0  # registration takes 3 ticks
        seen_claims = set(env.kube.node_claims)
        for _ in range(70):
            env.step(2.0)
            seen_claims |= set(env.kube.node_claims)
            pending = env.kube.pending_pods()
            if pending:
                # a pod may only be pending while its replacement target
                # is ALREADY registered and ready (rebind window) — never
                # because capacity was torn down early
                ready_new = [
                    n
                    for name, n in env.kube.nodes.items()
                    if name not in before and n.ready
                ]
                assert ready_new, "pods pending with no replacement up"
            if not env.kube.pending_pods() and len(env.kube.node_claims) == 1:
                break
        assert len(env.kube.node_claims) == 1
        (claim,) = env.kube.node_claims.values()
        assert claim.name not in before  # it IS the replacement
        assert not env.kube.pending_pods()
        # exactly ONE replacement was ever launched: the just-ready
        # replacement must never itself be consolidated away (which would
        # force a third claim to cover the capacity gap)
        assert len(seen_claims - before) == 1, seen_claims - before
        for p in kept:
            assert env.kube.pods[p.key()].node_name == claim.name
        # strictly cheaper
        assert claim.price > 0

    def test_nominate_later_entries_age_out(self, env):
        """A pod that never drains off its candidate (e.g. permanently
        PDB-blocked) must not protect its replacement target forever
        (round-3 ADVICE): the nomination ledger entry ages out after the
        replacement timeout."""
        from karpenter_tpu.controllers.disruption import (
            REPLACEMENT_TIMEOUT,
            _Nomination,
        )

        env.default_node_class()
        env.default_node_pool()
        pod = Pod(requests=Resources(cpu=1))
        env.kube.put_pod(pod)
        env.settle()
        node_name = env.kube.pods[pod.key()].node_name
        assert node_name
        dc = env.operator.disruption
        # simulate a reaped replacement whose pod is stuck on the candidate
        dc._nominate_later[pod.key()] = _Nomination(
            "replacement-target", (node_name,), env.clock.now()
        )
        env.step(1.0)
        # still bound to the candidate within the window: keep waiting
        assert pod.key() in dc._nominate_later
        env.clock.step(REPLACEMENT_TIMEOUT + 1.0)
        env.step(1.0)
        assert pod.key() not in dc._nominate_later

    def test_late_draining_pod_still_nominated(self, env):
        """A pod that finally drains AFTER the replacement timeout must be
        nominated onto its target, not silently dropped — the age-out only
        applies while the pod is stuck on a draining candidate."""
        from karpenter_tpu.controllers.disruption import (
            REPLACEMENT_TIMEOUT,
            _Nomination,
        )

        env.default_node_class()
        env.default_node_pool()
        pod = Pod(requests=Resources(cpu=1))
        env.kube.put_pod(pod)
        env.settle()
        node_name = env.kube.pods[pod.key()].node_name
        dc = env.operator.disruption
        entry_ts = env.clock.now()
        env.clock.step(REPLACEMENT_TIMEOUT + 10.0)
        # the pod drains (re-pends) just after the deadline, before the
        # next reconcile observes the stale entry
        pod.node_name = ""
        pod.phase = "Pending"
        dc._nominate_later[pod.key()] = _Nomination(
            "some-target", (node_name,), entry_ts
        )
        # target doesn't exist as claim/node -> entry is dropped cleanly;
        # register a claim so nomination has a live target instead
        dc._nominate_evicted()
        # with no such target the ledger entry is removed without nominating
        assert pod.key() not in dc._nominate_later
        # now with a live target: nomination must happen despite the age
        target = next(iter(env.kube.node_claims))
        dc._nominate_later[pod.key()] = _Nomination(
            target, (node_name,), entry_ts
        )
        dc._nominate_evicted()
        assert pod.key() not in dc._nominate_later
        assert env.cluster.nominated_node(pod.key()) == target

    def test_expiration_runs_in_same_pass_as_reap(self, env):
        """A resolving replacement must not defer expiration/drift/emptiness
        for the whole pass (round-3 ADVICE): only consolidation is skipped
        after a reap acts."""
        from karpenter_tpu.controllers.disruption import _PendingReplacement

        env.default_node_class()
        env.default_node_pool(disruption=Disruption(expire_after=100.0))
        pod = Pod(requests=Resources(cpu=1))
        env.kube.put_pod(pod)
        env.settle()
        (claim,) = env.kube.node_claims.values()
        env.clock.step(200.0)  # the node is now past expire_after
        dc = env.operator.disruption
        # a ready in-flight replacement that will be reaped this pass
        dc._pending[claim.name] = _PendingReplacement(
            claim_name=claim.name,
            candidate_names=[],
            pod_keys=[],
            created_at=env.clock.now(),
            reason="test",
        )
        dc.reconcile()
        assert not dc._pending  # the reap acted...
        assert claim.deleted_at is not None  # ...and expiration still ran

    def test_reaped_replacement_protected_from_expiration(self, env):
        """The pass that runs expiration after a reap must NOT expire the
        just-reaped replacement itself: its nomination targets keep it in
        `protected` until the drained pods bind."""
        from karpenter_tpu.controllers.disruption import _PendingReplacement

        env.default_node_class()
        env.default_node_pool(disruption=Disruption(expire_after=100.0))
        p1 = Pod(requests=Resources(cpu=28, memory="48Gi"))
        env.kube.put_pod(p1)
        env.settle()
        (b_name,) = env.kube.node_claims  # the "replacement" node
        p2 = Pod(requests=Resources(cpu=28, memory="48Gi"))
        env.kube.put_pod(p2)  # forces a second node A
        env.settle()
        assert len(env.kube.node_claims) == 2
        b_claim = env.kube.node_claims[b_name]
        assert b_claim.deleted_at is None
        # backdate B past expire_after (advancing the clock instead would
        # let settle()'s own reconciles expire it before the reap pass)
        b_claim.created_at = env.clock.now() - 200.0
        dc = env.operator.disruption
        dc._pending[b_name] = _PendingReplacement(
            claim_name=b_name,
            candidate_names=[],
            pod_keys=[p1.key()],
            created_at=env.clock.now(),
            reason="test",
        )
        dc.reconcile()
        assert not dc._pending  # reaped
        # B is the only expired node, but it is protected by its pending
        # nomination — expiration must not tear it down
        assert p1.key() in dc._nominate_later
        assert dc._nominate_later[p1.key()].target == b_name
        assert env.kube.node_claims[b_name].deleted_at is None

    def test_rollback_when_replacement_never_registers(self, env):
        """A replacement that never comes up is rolled back: the candidate
        stays, its pods never move."""
        kept = _replace_scenario(env)
        before = dict(env.kube.node_claims)
        env.kubelet.startup_delay = float("inf")  # nothing registers anymore
        # let consolidation launch the replacement
        replacement = None
        for _ in range(10):
            env.step(2.0)
            new = set(env.kube.node_claims) - set(before)
            if new:
                replacement = next(iter(new))
                break
        assert replacement is not None, "no replacement launched"
        # candidates untouched while the replacement is pending
        for name, claim in before.items():
            if name in env.kube.node_claims:
                assert env.kube.node_claims[name].deleted_at is None
        # blow past the registration timeout -> rollback
        for _ in range(8):
            env.step(100.0)
        assert replacement not in env.kube.node_claims
        # original capacity still intact, pods still running where they were
        assert set(env.kube.node_claims) & set(before)
        for p in kept:
            assert env.kube.pods[p.key()].node_name
        assert not env.kube.pending_pods()


class TestPerPoolPreSpin:
    def test_second_pool_consolidates_during_first_pool_prespin(self, env):
        """A slow-registering replacement in pool A must not freeze
        consolidation in pool B: the in-flight gate is per TARGET pool
        (disruption.py pending_pools), not cluster-wide."""
        env.default_node_class()
        for name in ("pool-a", "pool-b"):
            env.default_node_pool(
                name=name,
                requirements=Requirements(
                    [
                        Requirement(L.LABEL_NODEPOOL, Op.IN, [name]),
                        Requirement(
                            L.LABEL_CAPACITY_TYPE,
                            Op.IN,
                            [L.CAPACITY_TYPE_ON_DEMAND],
                        ),
                        Requirement(L.LABEL_INSTANCE_CPU, Op.GT, ["31"]),
                    ]
                ),
                disruption=Disruption(consolidation_policy="WhenUnderutilized"),
            )
        pods = {}
        for name in ("pool-a", "pool-b"):
            pods[name] = [
                Pod(
                    requests=Resources(cpu=4, memory="8Gi"),
                    node_selector={L.LABEL_NODEPOOL: name},
                )
                for _ in range(16)
            ]
            for p in pods[name]:
                env.kube.put_pod(p)
        env.settle()
        assert not env.kube.pending_pods()
        # shrink both pools' workloads so each big node is oversized
        for name in ("pool-a", "pool-b"):
            for p in pods[name][2:]:
                env.kube.delete_pod(p.key())
            pool = env.kube.node_pools[name]
            pool.requirements = Requirements(
                [
                    Requirement(L.LABEL_NODEPOOL, Op.IN, [name]),
                    Requirement(
                        L.LABEL_CAPACITY_TYPE,
                        Op.IN,
                        [L.CAPACITY_TYPE_ON_DEMAND],
                    ),
                ]
            )
        env.kubelet.startup_delay = 6.0  # replacements register slowly
        for _ in range(120):
            env.step(2.0)
            claims = env.kube.node_claims.values()
            by_pool = {}
            for c in claims:
                by_pool.setdefault(c.pool_name, []).append(c)
            if (
                not env.kube.pending_pods()
                and len(by_pool.get("pool-a", [])) == 1
                and len(by_pool.get("pool-b", [])) == 1
            ):
                break
        by_pool = {}
        for c in env.kube.node_claims.values():
            by_pool.setdefault(c.pool_name, []).append(c)
        # BOTH pools consolidated to their single cheap replacement —
        # neither waited on the other's in-flight registration
        assert len(by_pool.get("pool-a", [])) == 1, by_pool
        assert len(by_pool.get("pool-b", [])) == 1, by_pool
        assert not env.kube.pending_pods()
