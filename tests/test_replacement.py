"""Consolidation replacement pre-spin tests (round-2 VERDICT item #3).

The reference launches the replacement node and waits for it to be ready
before terminating the candidate (designs/consolidation.md:5-21,
website v0.31 deprovisioning.md:83-110).  These specs assert the no-gap
ordering — candidate deletion only starts after the replacement is up —
and rollback when the replacement never registers.
"""

import pytest

from karpenter_tpu.api import Disruption, Pod, Requirement, Requirements, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    return Environment()


def _replace_scenario(env):
    """One lightly-loaded on-demand node whose pods fit a strictly cheaper
    replacement: the single-node replace case."""
    env.default_node_class()
    env.default_node_pool(
        requirements=Requirements(
            [
                Requirement(
                    L.LABEL_CAPACITY_TYPE, Op.IN, [L.CAPACITY_TYPE_ON_DEMAND]
                ),
                # big nodes only, so the initial fleet overshoots
                Requirement(L.LABEL_INSTANCE_CPU, Op.GT, ["31"]),
            ]
        ),
        disruption=Disruption(consolidation_policy="WhenUnderutilized"),
    )
    pods = [
        Pod(requests=Resources(cpu=4, memory="8Gi")) for _ in range(16)
    ]
    for p in pods:
        env.kube.put_pod(p)
    env.settle()
    assert not env.kube.pending_pods()
    # shrink the workload to 2 small pods -> one big node is now oversized
    for p in pods[2:]:
        env.kube.delete_pod(p.key())
    # relax the pool so a small replacement is allowed
    pool = env.kube.node_pools["default"]
    pool.requirements = Requirements(
        [Requirement(L.LABEL_CAPACITY_TYPE, Op.IN, [L.CAPACITY_TYPE_ON_DEMAND])]
    )
    return pods[:2]


class TestReplacementPreSpin:
    def test_candidate_survives_until_replacement_ready(self, env):
        """While the replacement registers, the candidate must stay alive
        (capacity never dips); pods land on the replacement afterwards."""
        kept = _replace_scenario(env)
        before = set(env.kube.node_claims)
        env.kubelet.startup_delay = 6.0  # registration takes 3 ticks
        seen_claims = set(env.kube.node_claims)
        for _ in range(70):
            env.step(2.0)
            seen_claims |= set(env.kube.node_claims)
            pending = env.kube.pending_pods()
            if pending:
                # a pod may only be pending while its replacement target
                # is ALREADY registered and ready (rebind window) — never
                # because capacity was torn down early
                ready_new = [
                    n
                    for name, n in env.kube.nodes.items()
                    if name not in before and n.ready
                ]
                assert ready_new, "pods pending with no replacement up"
            if not env.kube.pending_pods() and len(env.kube.node_claims) == 1:
                break
        assert len(env.kube.node_claims) == 1
        (claim,) = env.kube.node_claims.values()
        assert claim.name not in before  # it IS the replacement
        assert not env.kube.pending_pods()
        # exactly ONE replacement was ever launched: the just-ready
        # replacement must never itself be consolidated away (which would
        # force a third claim to cover the capacity gap)
        assert len(seen_claims - before) == 1, seen_claims - before
        for p in kept:
            assert env.kube.pods[p.key()].node_name == claim.name
        # strictly cheaper
        assert claim.price > 0

    def test_rollback_when_replacement_never_registers(self, env):
        """A replacement that never comes up is rolled back: the candidate
        stays, its pods never move."""
        kept = _replace_scenario(env)
        before = dict(env.kube.node_claims)
        env.kubelet.startup_delay = float("inf")  # nothing registers anymore
        # let consolidation launch the replacement
        replacement = None
        for _ in range(10):
            env.step(2.0)
            new = set(env.kube.node_claims) - set(before)
            if new:
                replacement = next(iter(new))
                break
        assert replacement is not None, "no replacement launched"
        # candidates untouched while the replacement is pending
        for name, claim in before.items():
            if name in env.kube.node_claims:
                assert env.kube.node_claims[name].deleted_at is None
        # blow past the registration timeout -> rollback
        for _ in range(8):
            env.step(100.0)
        assert replacement not in env.kube.node_claims
        # original capacity still intact, pods still running where they were
        assert set(env.kube.node_claims) & set(before)
        for p in kept:
            assert env.kube.pods[p.key()].node_name
        assert not env.kube.pending_pods()
