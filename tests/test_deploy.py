"""Deployment story (reference charts/karpenter): the chart renders to
valid manifests, and the rendered settings configmap actually loads as
the controller's Settings — the chart and the binary cannot drift."""

import json

import pytest
import yaml

from karpenter_tpu.api import Settings
from karpenter_tpu.tools.render_chart import render_chart, render_template

CHART = "deploy/chart"
SET = {"settings.cluster_name": "prod-cluster"}


def _docs():
    out = []
    for rendered in render_chart(CHART, dict(SET)):
        out.extend(d for d in yaml.safe_load_all(rendered) if d)
    return {(d["kind"], d["metadata"]["name"]): d for d in out}


class TestChart:
    def test_renders_all_expected_kinds(self):
        docs = _docs()
        kinds = {k for k, _ in docs}
        assert kinds == {
            "Deployment", "Service", "ConfigMap",
            "ServiceAccount", "PodDisruptionBudget",
        }
        # controller + solver deployments, metrics + solver services
        assert ("Deployment", "karpenter-tpu") in docs
        assert ("Deployment", "karpenter-tpu-solver") in docs

    def test_rendered_settings_load_as_real_settings(self, tmp_path):
        """The configmap's settings.json must be accepted verbatim by
        Settings.from_file — unknown keys or bad types fail the test,
        so the chart can't drift from the binary."""
        docs = _docs()
        cm = docs[("ConfigMap", "karpenter-tpu-global-settings")]
        payload = cm["data"]["settings.json"]
        path = tmp_path / "settings.json"
        path.write_text(payload)
        settings = Settings.from_file(str(path))
        settings.validate()
        assert settings.cluster_name == "prod-cluster"
        assert settings.batch_idle_duration == 1.0
        assert settings.enable_profiling is False

    def test_controller_matches_entry_point_contract(self):
        docs = _docs()
        dep = docs[("Deployment", "karpenter-tpu")]
        assert dep["spec"]["replicas"] == 2  # reference Makefile:25-28
        (c,) = dep["spec"]["template"]["spec"]["containers"]
        assert c["command"] == ["python", "-m", "karpenter_tpu"]
        assert any(a.startswith("--settings-file=") for a in c["args"])
        assert any(a.startswith("--solver-address=") for a in c["args"])
        port = c["ports"][0]["containerPort"]
        assert c["livenessProbe"]["httpGet"]["port"] == port
        assert c["resources"]["requests"] == {"cpu": "1", "memory": "1Gi"}
        # settings volume wired to the rendered configmap
        (vol,) = dep["spec"]["template"]["spec"]["volumes"]
        assert vol["configMap"]["name"] == "karpenter-tpu-global-settings"

    def test_solver_requests_accelerator(self):
        docs = _docs()
        dep = docs[("Deployment", "karpenter-tpu-solver")]
        (c,) = dep["spec"]["template"]["spec"]["containers"]
        assert c["resources"]["limits"] == {"google.com/tpu": 1}
        assert c["command"][-1] == "karpenter_tpu.service.server"

    def test_selectors_line_up(self):
        """Service and PDB selectors must match the deployment labels
        (the classic copy-paste drift a chart test exists to catch)."""
        docs = _docs()
        dep_labels = docs[("Deployment", "karpenter-tpu")]["spec"]["template"][
            "metadata"
        ]["labels"]
        svc = docs[("Service", "karpenter-tpu")]["spec"]["selector"]
        pdb = docs[("PodDisruptionBudget", "karpenter-tpu")]["spec"][
            "selector"
        ]["matchLabels"]
        assert svc.items() <= dep_labels.items()
        assert pdb.items() <= dep_labels.items()
        solver_dep = docs[("Deployment", "karpenter-tpu-solver")]
        solver_svc = docs[("Service", "karpenter-tpu-solver")]["spec"][
            "selector"
        ]
        assert (
            solver_svc.items()
            <= solver_dep["spec"]["template"]["metadata"]["labels"].items()
        )

    def test_set_overrides(self):
        docs = {}
        for rendered in render_chart(
            CHART, {**SET, "replicas": "3", "solver.port": "9999"}
        ):
            for d in yaml.safe_load_all(rendered):
                if d:
                    docs[(d["kind"], d["metadata"]["name"])] = d
        assert docs[("Deployment", "karpenter-tpu")]["spec"]["replicas"] == 3
        c = docs[("Deployment", "karpenter-tpu")]["spec"]["template"]["spec"][
            "containers"
        ][0]
        assert any(a.endswith(":9999") for a in c["args"])

    def test_unknown_values_path_is_an_error(self):
        with pytest.raises(KeyError):
            render_template("x: {{ .Values.not.a.path }}", {"not": {}})

    def test_leftover_template_expression_is_an_error(self):
        with pytest.raises(ValueError):
            render_template("x: {{ include \"helper\" . }}", {})

    def test_solver_binds_all_interfaces(self):
        """server.py defaults to loopback; the chart must override or the
        solver Service can never reach the pod."""
        docs = _docs()
        c = docs[("Deployment", "karpenter-tpu-solver")]["spec"]["template"][
            "spec"
        ]["containers"][0]
        assert "--host=0.0.0.0" in c["args"]

    def test_bad_json_in_settings_fails_at_render_time(self):
        with pytest.raises(ValueError, match="not valid JSON"):
            render_chart(CHART, {"settings.cluster_name": 'evil"quote'})
