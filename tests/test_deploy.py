"""Deployment story (reference charts/karpenter): the chart renders to
valid manifests, and the rendered settings configmap actually loads as
the controller's Settings — the chart and the binary cannot drift."""

import json

import pytest
import yaml

from karpenter_tpu.api import Settings
from karpenter_tpu.tools.render_chart import render_chart, render_template

CHART = "deploy/chart"
SET = {"settings.cluster_name": "prod-cluster"}


def _docs():
    out = []
    for rendered in render_chart(CHART, dict(SET)):
        out.extend(d for d in yaml.safe_load_all(rendered) if d)
    return {(d["kind"], d["metadata"]["name"]): d for d in out}


class TestChart:
    def test_renders_all_expected_kinds(self):
        docs = _docs()
        kinds = {k for k, _ in docs}
        assert kinds == {
            "Deployment", "Service", "ConfigMap",
            "ServiceAccount", "PodDisruptionBudget",
            "Role", "RoleBinding", "ClusterRole", "ClusterRoleBinding",
        }
        # controller + solver deployments, metrics + solver services
        assert ("Deployment", "karpenter-tpu") in docs
        assert ("Deployment", "karpenter-tpu-solver") in docs

    def test_rbac_binds_the_service_account(self):
        """Every binding targets the chart's ServiceAccount, and the
        namespace Role covers the leader-election Lease the controller
        takes (utils/leader.py LEASE_NAME)."""
        docs = _docs()
        sa = docs[("ServiceAccount", "karpenter-tpu")]["metadata"]["name"]
        for kind in ("RoleBinding", "ClusterRoleBinding"):
            for (k, _), d in docs.items():
                if k != kind:
                    continue
                subjects = d["subjects"]
                assert any(
                    s["kind"] == "ServiceAccount" and s["name"] == sa
                    for s in subjects
                ), d["metadata"]["name"]
        role = docs[("Role", "karpenter-tpu")]
        from karpenter_tpu.utils.leader import LEASE_NAME

        lease_rules = [
            r
            for r in role["rules"]
            if "leases" in r.get("resources", ())
            and "resourceNames" in r
        ]
        assert lease_rules, "no lease write rule"
        assert any(
            LEASE_NAME in r["resourceNames"] for r in lease_rules
        ), (LEASE_NAME, lease_rules)

    # every reference chart template has an analogue here or an explicit
    # waiver (reference charts/karpenter/templates/)
    REFERENCE_TEMPLATES = {
        "_helpers.tpl": "waived: renderer is plain {{ .Values }} "
        "substitution (tools/render_chart.py), no helper layer",
        "aggregate-clusterrole.yaml": "aggregate-clusterrole.yaml",
        "clusterrole-core.yaml": "clusterrole-core.yaml",
        "clusterrole.yaml": "clusterrole.yaml",
        "configmap-logging.yaml": "waived: logging configured via the "
        "settings configmap (api/settings.py), no zap config",
        "configmap.yaml": "configmap.yaml",
        "deployment.yaml": "deployment.yaml",
        "poddisruptionbudget.yaml": "poddisruptionbudget.yaml",
        "role.yaml": "role.yaml",
        "rolebinding.yaml": "rolebinding.yaml",
        "secret-webhook-cert.yaml": "waived: admission validation runs "
        "in-process (api/validation.py on KubeStore writes), no webhook "
        "serving certs",
        "service.yaml": "service.yaml",
        "serviceaccount.yaml": "serviceaccount.yaml",
        "servicemonitor.yaml": "waived: /metrics is a plain HTTP scrape "
        "target on the metrics Service; no Prometheus Operator coupling",
        "webhooks-core.yaml": "waived: in-process admission",
        "webhooks.yaml": "waived: in-process admission",
    }

    def test_every_reference_template_mapped_or_waived(self):
        import pathlib

        tpl_dir = pathlib.Path(CHART) / "templates"
        have = {p.name for p in tpl_dir.glob("*.yaml")}
        for ref, target in self.REFERENCE_TEMPLATES.items():
            if target.startswith("waived"):
                continue
            assert target in have, (ref, target)
        # and nothing unmapped sneaks in: every local template is some
        # reference analogue or the solver sidecar (no reference
        # counterpart — the distributed TPU backend is this build's own)
        mapped = {
            t
            for t in self.REFERENCE_TEMPLATES.values()
            if not t.startswith("waived")
        }
        extras = have - mapped
        # solver sidecar + store backend: no reference counterparts — the
        # reference's solver doesn't exist and its durable store IS the
        # kube-apiserver; both are this build's own distributed halves
        assert extras == {
            "solver-deployment.yaml",
            "store-deployment.yaml",
            "store-replica.yaml",
            "store-service.yaml",
        }, extras

    def test_rendered_settings_load_as_real_settings(self, tmp_path):
        """The configmap's settings.json must be accepted verbatim by
        Settings.from_file — unknown keys or bad types fail the test,
        so the chart can't drift from the binary."""
        docs = _docs()
        cm = docs[("ConfigMap", "karpenter-tpu-global-settings")]
        payload = cm["data"]["settings.json"]
        path = tmp_path / "settings.json"
        path.write_text(payload)
        settings = Settings.from_file(str(path))
        settings.validate()
        assert settings.cluster_name == "prod-cluster"
        assert settings.provision_batch_idle_s == 1.0
        assert settings.enable_pipelined_reconcile is True
        assert settings.launch_max_concurrency == 64
        assert settings.enable_profiling is False
        # the admission fast path's chart knobs flow end to end
        assert settings.enable_admission_fastpath is True
        assert settings.provision_fastpath_bypass is True

    def test_controller_matches_entry_point_contract(self):
        docs = _docs()
        dep = docs[("Deployment", "karpenter-tpu")]
        # SAFE default: 1 replica — the reference ships 2, but its durable
        # store is the apiserver; here 2 is legitimate only with the
        # shared-store backend (see TestStoreBackend.test_ha_render)
        assert dep["spec"]["replicas"] == 1
        (c,) = dep["spec"]["template"]["spec"]["containers"]
        assert c["command"] == ["python", "-m", "karpenter_tpu"]
        assert any(a.startswith("--settings-file=") for a in c["args"])
        assert any(a.startswith("--solver-address=") for a in c["args"])
        # no store backend by default -> no store client flag
        assert not any(a.startswith("--store-address=") for a in c["args"])
        port = c["ports"][0]["containerPort"]
        assert c["livenessProbe"]["httpGet"]["port"] == port
        assert c["resources"]["requests"] == {"cpu": "1", "memory": "1Gi"}
        # settings volume wired to the rendered configmap
        (vol,) = dep["spec"]["template"]["spec"]["volumes"]
        assert vol["configMap"]["name"] == "karpenter-tpu-global-settings"

    def test_solver_requests_accelerator(self):
        docs = _docs()
        dep = docs[("Deployment", "karpenter-tpu-solver")]
        (c,) = dep["spec"]["template"]["spec"]["containers"]
        assert c["resources"]["limits"] == {"google.com/tpu": 1}
        assert c["command"][-1] == "karpenter_tpu.service.server"

    def test_selectors_line_up(self):
        """Service and PDB selectors must match the deployment labels
        (the classic copy-paste drift a chart test exists to catch)."""
        docs = _docs()
        dep_labels = docs[("Deployment", "karpenter-tpu")]["spec"]["template"][
            "metadata"
        ]["labels"]
        svc = docs[("Service", "karpenter-tpu")]["spec"]["selector"]
        pdb = docs[("PodDisruptionBudget", "karpenter-tpu")]["spec"][
            "selector"
        ]["matchLabels"]
        assert svc.items() <= dep_labels.items()
        assert pdb.items() <= dep_labels.items()
        solver_dep = docs[("Deployment", "karpenter-tpu-solver")]
        solver_svc = docs[("Service", "karpenter-tpu-solver")]["spec"][
            "selector"
        ]
        assert (
            solver_svc.items()
            <= solver_dep["spec"]["template"]["metadata"]["labels"].items()
        )

    def test_set_overrides(self):
        docs = {}
        for rendered in render_chart(
            CHART,
            {
                **SET,
                "replicas": "3",
                "solver.port": "9999",
                # replicas > 1 only renders with the shared store backend
                "store.enabled": "true",
            },
        ):
            for d in yaml.safe_load_all(rendered):
                if d:
                    docs[(d["kind"], d["metadata"]["name"])] = d
        assert docs[("Deployment", "karpenter-tpu")]["spec"]["replicas"] == 3
        c = docs[("Deployment", "karpenter-tpu")]["spec"]["template"]["spec"][
            "containers"
        ][0]
        assert any(a.endswith(":9999") for a in c["args"])

    def test_unknown_values_path_is_an_error(self):
        with pytest.raises(KeyError):
            render_template("x: {{ .Values.not.a.path }}", {"not": {}})

    def test_leftover_template_expression_is_an_error(self):
        with pytest.raises(ValueError):
            render_template("x: {{ include \"helper\" . }}", {})

    def test_solver_binds_all_interfaces(self):
        """server.py defaults to loopback; the chart must override or the
        solver Service can never reach the pod."""
        docs = _docs()
        c = docs[("Deployment", "karpenter-tpu-solver")]["spec"]["template"][
            "spec"
        ]["containers"][0]
        assert "--host=0.0.0.0" in c["args"]

    def test_bad_json_in_settings_fails_at_render_time(self):
        with pytest.raises(ValueError, match="not valid JSON"):
            render_chart(CHART, {"settings.cluster_name": 'evil"quote'})


class TestStoreBackend:
    """The shared cluster-store backend (ADVICE r5 medium): replicas > 1
    is legitimate only when the replicas actually share durable state."""

    def _docs(self, overrides):
        docs = {}
        for rendered in render_chart(CHART, {**SET, **overrides}):
            for d in yaml.safe_load_all(rendered):
                if d:
                    docs[(d["kind"], d["metadata"]["name"])] = d
        return docs

    def test_default_renders_no_store(self):
        docs = self._docs({})
        assert ("Deployment", "karpenter-tpu-store") not in docs
        assert ("Service", "karpenter-tpu-store") not in docs

    def test_two_replicas_without_store_refuses_to_render(self):
        with pytest.raises(ValueError, match="store.enabled"):
            render_chart(CHART, {**SET, "replicas": "2"})

    def test_ha_render(self):
        """replicas: 2 + store.enabled: the full HA shape — shared store
        Deployment/Service, controllers dialing it as clients."""
        docs = self._docs({"replicas": "2", "store.enabled": "true"})
        dep = docs[("Deployment", "karpenter-tpu")]
        assert dep["spec"]["replicas"] == 2
        (c,) = dep["spec"]["template"]["spec"]["containers"]
        assert "--store-address=karpenter-tpu-store:8082" in c["args"]
        store = docs[("Deployment", "karpenter-tpu-store")]
        # the store is the durable single point: one replica, Recreate
        assert store["spec"]["replicas"] == 1
        assert store["spec"]["strategy"]["type"] == "Recreate"
        (sc,) = store["spec"]["template"]["spec"]["containers"]
        assert sc["command"][-1] == "store-server"
        assert "--host=0.0.0.0" in sc["args"]
        svc = docs[("Service", "karpenter-tpu-store")]
        assert (
            svc["spec"]["selector"].items()
            <= store["spec"]["template"]["metadata"]["labels"].items()
        )
        assert svc["spec"]["ports"][0]["port"] == 8082

    def test_store_alone_renders_without_ha(self):
        """store.enabled with replicas: 1 is fine (rolling toward HA)."""
        docs = self._docs({"store.enabled": "true"})
        assert ("Deployment", "karpenter-tpu-store") in docs
        dep = docs[("Deployment", "karpenter-tpu")]
        (c,) = dep["spec"]["template"]["spec"]["containers"]
        assert any(a.startswith("--store-address=") for a in c["args"])

    def test_store_carries_fleet_scale_bounds(self):
        """The store-scale knobs (docs/designs/store-scale.md) flow from
        values to server flags, and the settings configmap carries the
        matching client posture."""
        docs = self._docs(
            {"store.enabled": "true", "store.replayLogEvents": "8192"}
        )
        (sc,) = docs[("Deployment", "karpenter-tpu-store")]["spec"][
            "template"
        ]["spec"]["containers"]
        assert "--replay-log-events=8192" in sc["args"]
        assert "--watch-queue-batches=256" in sc["args"]
        assert "--events-cap=4096" in sc["args"]
        import json

        settings = json.loads(
            docs[("ConfigMap", "karpenter-tpu-global-settings")]["data"][
                "settings.json"
            ]
        )
        assert settings["store_codec"] == "auto"
        assert settings["store_events_cap"] == 4096

    def test_read_replica_toggle(self):
        """store.readReplica.enabled renders a follower Deployment +
        Service dialing the primary with --replica-of; off by default;
        refused without the primary."""
        docs = self._docs({"store.enabled": "true"})
        assert ("Deployment", "karpenter-tpu-store-replica") not in docs
        docs = self._docs(
            {"store.enabled": "true", "store.readReplica.enabled": "true"}
        )
        rep = docs[("Deployment", "karpenter-tpu-store-replica")]
        (rc,) = rep["spec"]["template"]["spec"]["containers"]
        assert "--replica-of=karpenter-tpu-store:8082" in rc["args"]
        svc = docs[("Service", "karpenter-tpu-store-replica")]
        assert (
            svc["spec"]["selector"].items()
            <= rep["spec"]["template"]["metadata"]["labels"].items()
        )
        with pytest.raises(ValueError, match="readReplica"):
            render_chart(
                CHART, {**SET, "store.readReplica.enabled": "true"}
            )


class TestCRDs:
    """CRD-install story (reference charts/karpenter-crd/): schemas are
    GENERATED from the api/objects.py dataclasses — regeneration must be
    a no-op, and the documented rbac/chart names must agree."""

    def test_crds_match_source(self):
        import pathlib

        from karpenter_tpu.tools.gen_crds import generate

        crd_dir = pathlib.Path("deploy/crds")
        want = generate()
        have = {p.name: p.read_text() for p in crd_dir.glob("*.yaml")}
        assert have == want

    def test_crd_names_cover_rbac_resources(self):
        import pathlib

        import yaml as _yaml

        from karpenter_tpu.tools.gen_crds import GROUP

        plurals = set()
        for p in pathlib.Path("deploy/crds").glob("*.yaml"):
            doc = _yaml.safe_load(p.read_text())
            assert doc["spec"]["group"] == GROUP
            plurals.add(doc["spec"]["names"]["plural"])
        assert plurals == {"nodepools", "nodeclaims", "nodeclasses"}
        # the clusterroles grant exactly these resources under the group
        docs = _docs()
        granted = set()
        for (kind, _), d in docs.items():
            if kind != "ClusterRole":
                continue
            for rule in d["rules"]:
                if GROUP in rule.get("apiGroups", ()):
                    granted.update(
                        r for r in rule["resources"] if "/" not in r
                    )
        assert plurals <= granted, (plurals, granted)
