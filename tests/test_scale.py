"""Scale suite: the reference's scale-test configs run in-process.

Reference test/suites/scale/provisioning_test.go:92-173 runs these against
a live EKS cluster with a 30-minute SpecTimeout; the fake-cloud equivalents
complete in seconds, which is the point — the solve itself is the
bottleneck the TPU kernel removes.
"""

import pytest

from karpenter_tpu.api import (
    Disruption,
    NodeClass,
    NodePool,
    Pod,
    Requirement,
    Requirements,
    Resources,
)
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm, SelectorTerm, Taint, Toleration
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.testing import Environment


class TestScaleProvisioning:
    def test_node_dense_500_nodes(self):
        """500 pods, one per node via hostname anti-affinity
        (reference provisioning_test.go:92-135)."""
        env = Environment()
        env.default_node_class()
        env.default_node_pool()
        sel = (("app", "dense"),)
        for _ in range(500):
            env.kube.put_pod(
                Pod(
                    labels={"app": "dense"},
                    requests=Resources(cpu=0.5, memory="256Mi"),
                    pod_affinity=[
                        PodAffinityTerm(
                            topology_key=L.LABEL_HOSTNAME,
                            label_selector=sel,
                            anti=True,
                        )
                    ],
                )
            )
        env.settle(max_rounds=10)
        assert not env.kube.pending_pods()
        assert len(env.kube.node_claims) == 500
        assert len(env.kube.nodes) == 500
        # anti-affinity honored: exactly one app=dense pod per node
        per_node = {}
        for p in env.kube.pods.values():
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
        assert max(per_node.values()) == 1

    def test_pod_dense_6600_pods_60_nodes(self):
        """6,600 pods at 110 pods/node -> exactly 60 nodes
        (reference provisioning_test.go:136-173)."""
        env = Environment()
        env.default_node_class()
        env.default_node_pool(kubelet_max_pods=110)
        for _ in range(6600):
            env.kube.put_pod(Pod(requests=Resources(cpu=0.1, memory="128Mi")))
        env.settle(max_rounds=10)
        assert not env.kube.pending_pods()
        assert len(env.kube.node_claims) == 60
        for c in env.kube.node_claims.values():
            assert c.registered and c.initialized


class TestScaleDeprovisioning:
    def test_multi_mechanism_scale_down(self):
        """Consolidation shrinks a mostly-empty fleet
        (reference deprovisioning_test.go:349-691 family, in miniature)."""
        env = Environment()
        env.default_node_class()
        env.default_node_pool(
            requirements=Requirements(
                [Requirement(L.LABEL_INSTANCE_CPU, Op.LT, ["17"])]
            ),
            disruption=Disruption(consolidation_policy="WhenUnderutilized"),
        )
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(120)]
        for p in pods:
            env.kube.put_pod(p)
        env.settle(max_rounds=10)
        before = len(env.kube.node_claims)
        assert before >= 8
        for p in pods[10:]:
            env.kube.delete_pod(p.key())
        for _ in range(12):
            env.step(2.0)
        env.settle()
        after = len(env.kube.node_claims)
        assert after <= max(2, before // 3)
        assert not env.kube.pending_pods()
        # no instance leaks: running instances == live claims
        running = [
            i for i in env.cloud.instances.values() if i.state == "running"
        ]
        assert len(running) == after

    def test_four_mechanisms_simultaneously_520_pods(self):
        """Expiration, drift, emptiness, and consolidation all active in
        ONE run, each owning a taint-isolated pool, 520 pods total
        (reference deprovisioning_test.go:139-320 'should run
        consolidation, emptiness, expiration, and drift simultaneously').
        """
        mechs = ("consolidate", "empty", "expire", "drift")
        pods_per_mech = 130  # 4 x 130 = 520 pods
        env = Environment()
        env.default_node_class()
        drift_nc = NodeClass(
            name="drift-nc",
            subnet_selector_terms=[SelectorTerm.of(Name="*")],
            security_group_selector_terms=[SelectorTerm.of(Name="*")],
        )
        env.kube.put_node_class(drift_nc)
        for m in mechs:
            env.kube.put_node_pool(
                NodePool(
                    name=m,
                    node_class_ref="drift-nc" if m == "drift" else "default",
                    taints=[Taint("mech", m, "NoSchedule")],
                    labels={"mech": m},
                    requirements=Requirements(
                        [Requirement(L.LABEL_INSTANCE_CPU, Op.LT, ["17"])]
                    ),
                    kubelet_max_pods=25,
                    disruption=Disruption(
                        consolidation_policy="WhenEmpty"
                        if m == "empty"
                        else "WhenUnderutilized",
                        consolidate_after=0,
                        budgets=["100%"],
                    ),
                )
            )
        by_mech = {m: [] for m in mechs}
        for m in mechs:
            for _ in range(pods_per_mech):
                p = Pod(
                    labels={"mech": m},
                    node_selector={"mech": m},
                    tolerations=[Toleration(key="mech", value=m)],
                    requests=Resources(cpu=0.7, memory="700Mi"),
                )
                by_mech[m].append(p)
                env.kube.put_pod(p)
        env.settle(max_rounds=15)
        assert not env.kube.pending_pods()
        claims_by_pool = lambda: {
            m: {
                c.name
                for c in env.kube.node_claims.values()
                if c.pool_name == m and c.deleted_at is None
            }
            for m in mechs
        }
        before = claims_by_pool()
        for m in mechs:
            assert len(before[m]) >= 6, f"{m}: {len(before[m])} nodes"

        # fire all four mechanisms in the same window
        for p in by_mech["consolidate"][int(pods_per_mech * 0.2):]:
            env.kube.delete_pod(p.key())  # 80% shrink -> repack
        for p in by_mech["empty"]:
            env.kube.delete_pod(p.key())  # whole pool empties
        env.kube.node_pools["expire"].disruption.expire_after = 1.0
        drift_nc.user_data = "#drifted"  # static-hash change -> drift
        env.kube.put_node_class(drift_nc)

        for _ in range(120):
            env.step(2.0)
            if not env.kube.pending_pods():
                after = claims_by_pool()
                if (
                    not after["empty"]
                    and len(after["consolidate"]) <= len(before["consolidate"]) // 2
                    and not (after["expire"] & before["expire"])
                    and not (after["drift"] & before["drift"])
                ):
                    break
        after = claims_by_pool()
        # emptiness: every node of the emptied pool is gone
        assert after["empty"] == set()
        # consolidation: 80% fewer pods -> at most half the nodes remain
        assert len(after["consolidate"]) <= len(before["consolidate"]) // 2
        # expiration: full turnover, capacity still serving the pods
        assert not (after["expire"] & before["expire"])
        assert after["expire"], "expired nodes were not replaced"
        # drift: full turnover onto the new node-class hash
        assert not (after["drift"] & before["drift"])
        assert after["drift"], "drifted nodes were not replaced"
        assert not env.kube.pending_pods()
        # no instance leaks across ~60+ disruption actions
        running = [
            i for i in env.cloud.instances.values() if i.state == "running"
        ]
        live = [
            c for c in env.kube.node_claims.values() if c.deleted_at is None
        ]
        assert len(running) == len(live)


class TestChaos:
    def test_runaway_scale_up_bounded(self):
        """Adversarial taint-adder: every node is tainted the moment it
        registers, so the pod can never bind and every launch is wasted.
        Emptiness must keep deleting the tainted nodes and the fleet must
        stay BOUNDED — no runaway scale-up (reference
        test/suites/chaos/suite_test.go:67,112)."""
        env = Environment()
        env.default_node_class()
        env.default_node_pool(
            disruption=Disruption(
                consolidation_policy="WhenEmpty",
                consolidate_after=0,
                budgets=["100%"],
            )
        )
        orig_put = env.kube.put_node

        def tainted_put(node):
            if not any(t.key == "chaos" for t in node.taints):
                node.taints.append(Taint("chaos", "true", "NoSchedule"))
            return orig_put(node)

        env.kube.put_node = tainted_put
        env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="1Gi")))

        max_live = 0
        total_launched = set()
        for _ in range(80):
            env.step(2.0)
            live = [
                c
                for c in env.kube.node_claims.values()
                if c.deleted_at is None
            ]
            total_launched.update(c.name for c in live)
            max_live = max(max_live, len(live))
            # the reference asserts < 35 live nodes over 5 minutes; the
            # fake loop is tighter — a handful of in-flight nodes at most
            assert len(live) < 10, f"runaway: {len(live)} live nodes"
        assert max_live < 10
        # the pod never binds (every node is tainted), so its nomination
        # must EXPIRE, the provisioner must retry, and emptiness must reap
        # the abandoned tainted nodes: capacity CYCLES (many claims
        # launched over time) without ever ACCUMULATING (few live at once)
        assert env.kube.pending_pods()
        assert len(total_launched) > max_live, (
            f"no churn: {len(total_launched)} total vs {max_live} max live "
            "— the pod is deadlocked on a stale nomination"
        )
