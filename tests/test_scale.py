"""Scale suite: the reference's scale-test configs run in-process.

Reference test/suites/scale/provisioning_test.go:92-173 runs these against
a live EKS cluster with a 30-minute SpecTimeout; the fake-cloud equivalents
complete in seconds, which is the point — the solve itself is the
bottleneck the TPU kernel removes.
"""

import pytest

from karpenter_tpu.api import Disruption, Pod, Requirement, Requirements, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.testing import Environment


class TestScaleProvisioning:
    def test_node_dense_500_nodes(self):
        """500 pods, one per node via hostname anti-affinity
        (reference provisioning_test.go:92-135)."""
        env = Environment()
        env.default_node_class()
        env.default_node_pool()
        sel = (("app", "dense"),)
        for _ in range(500):
            env.kube.put_pod(
                Pod(
                    labels={"app": "dense"},
                    requests=Resources(cpu=0.5, memory="256Mi"),
                    pod_affinity=[
                        PodAffinityTerm(
                            topology_key=L.LABEL_HOSTNAME,
                            label_selector=sel,
                            anti=True,
                        )
                    ],
                )
            )
        env.settle(max_rounds=10)
        assert not env.kube.pending_pods()
        assert len(env.kube.node_claims) == 500
        assert len(env.kube.nodes) == 500
        # anti-affinity honored: exactly one app=dense pod per node
        per_node = {}
        for p in env.kube.pods.values():
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
        assert max(per_node.values()) == 1

    def test_pod_dense_6600_pods_60_nodes(self):
        """6,600 pods at 110 pods/node -> exactly 60 nodes
        (reference provisioning_test.go:136-173)."""
        env = Environment()
        env.default_node_class()
        env.default_node_pool(kubelet_max_pods=110)
        for _ in range(6600):
            env.kube.put_pod(Pod(requests=Resources(cpu=0.1, memory="128Mi")))
        env.settle(max_rounds=10)
        assert not env.kube.pending_pods()
        assert len(env.kube.node_claims) == 60
        for c in env.kube.node_claims.values():
            assert c.registered and c.initialized


class TestScaleDeprovisioning:
    def test_multi_mechanism_scale_down(self):
        """Consolidation shrinks a mostly-empty fleet
        (reference deprovisioning_test.go:349-691 family, in miniature)."""
        env = Environment()
        env.default_node_class()
        env.default_node_pool(
            requirements=Requirements(
                [Requirement(L.LABEL_INSTANCE_CPU, Op.LT, ["17"])]
            ),
            disruption=Disruption(consolidation_policy="WhenUnderutilized"),
        )
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(120)]
        for p in pods:
            env.kube.put_pod(p)
        env.settle(max_rounds=10)
        before = len(env.kube.node_claims)
        assert before >= 8
        for p in pods[10:]:
            env.kube.delete_pod(p.key())
        for _ in range(12):
            env.step(2.0)
        env.settle()
        after = len(env.kube.node_claims)
        assert after <= max(2, before // 3)
        assert not env.kube.pending_pods()
        # no instance leaks: running instances == live claims
        running = [
            i for i in env.cloud.instances.values() if i.state == "running"
        ]
        assert len(running) == after
