"""Fake cloud behaviors (reference pkg/fake/ec2api.go patterns)."""

import pytest

from karpenter_tpu.api.objects import SelectorTerm
from karpenter_tpu.cloud.fake.backend import (
    CloudAPIError,
    FakeCloud,
    MachineShape,
    generate_catalog,
)
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture
def cloud():
    return FakeCloud(
        FakeClock(),
        shapes=[
            MachineShape(name="std1.large", cpu=4, memory=16 * 2**30, od_price=0.2),
            MachineShape(name="std1.xlarge", cpu=8, memory=32 * 2**30, od_price=0.4),
        ],
        zones=["zone-a", "zone-b"],
    ).with_default_topology()


def test_create_fleet_and_describe(cloud):
    insts, errs = cloud.create_fleet(
        overrides=[{"instance_type": "std1.large", "zone": "zone-a", "subnet_id": "subnet-0"}],
        capacity_type="on-demand",
        count=3,
    )
    assert len(insts) == 3 and not errs
    assert len(cloud.describe_instances()) == 3
    assert cloud.subnets["subnet-0"].available_ips == 4096 - 3
    done = cloud.terminate_instances([insts[0].id])
    assert done == [insts[0].id]
    assert cloud.instances[insts[0].id].state == "terminated"
    assert cloud.subnets["subnet-0"].available_ips == 4096 - 2


def test_create_fleet_ice_fallback(cloud):
    cloud.mark_insufficient("std1.large", "zone-a", "on-demand")
    insts, errs = cloud.create_fleet(
        overrides=[
            {"instance_type": "std1.large", "zone": "zone-a", "subnet_id": "subnet-0"},
            {"instance_type": "std1.xlarge", "zone": "zone-b", "subnet_id": "subnet-1"},
        ],
        capacity_type="on-demand",
    )
    # falls through to the next-cheapest pool, reporting the ICE error
    assert len(insts) == 1 and insts[0].instance_type == "std1.xlarge"
    assert len(errs) == 1 and errs[0].pool == ("std1.large", "zone-a", "on-demand")


def test_create_fleet_capacity_pool_exhaustion(cloud):
    cloud.set_capacity("std1.large", "zone-a", "spot", 2)
    insts, errs = cloud.create_fleet(
        overrides=[{"instance_type": "std1.large", "zone": "zone-a", "subnet_id": "subnet-0"}],
        capacity_type="spot",
        count=5,
    )
    assert len(insts) == 2
    assert errs and errs[0].pool == ("std1.large", "zone-a", "spot")


def test_spot_cheaper_than_od(cloud):
    assert cloud.spot_price("std1.large", "zone-a") < cloud.on_demand_price("std1.large")


def test_selectors_and_images(cloud):
    subs = cloud.describe_subnets([SelectorTerm.of(Name="*")])
    assert len(subs) == 2
    img = cloud.latest_image("standard", "amd64")
    assert img is not None and img.arch == "amd64"


def test_next_error_injection(cloud):
    cloud.recorder.set_next_error("DescribeInstances", CloudAPIError("Throttled"))
    with pytest.raises(CloudAPIError):
        cloud.describe_instances()
    assert cloud.describe_instances() == []  # one-shot


def test_error_sequence_injection(cloud):
    """Sustained injection: the next N calls fail in order, then the API
    recovers (the one-shot NextError generalized)."""
    cloud.recorder.set_error_sequence(
        "DescribeInstances",
        [CloudAPIError("InternalError"), CloudAPIError("Throttling")],
    )
    with pytest.raises(CloudAPIError, match="InternalError"):
        cloud.describe_instances()
    with pytest.raises(CloudAPIError, match="Throttling"):
        cloud.describe_instances()
    assert cloud.describe_instances() == []


def test_error_at_call_injection(cloud):
    """Call-count-triggered injection: only the nth FUTURE call fails."""
    cloud.describe_instances()  # prior traffic must not shift the trigger
    cloud.recorder.set_error_at_call(
        "DescribeInstances", 3, CloudAPIError("InternalError")
    )
    cloud.describe_instances()
    cloud.describe_instances()
    with pytest.raises(CloudAPIError):
        cloud.describe_instances()
    assert cloud.describe_instances() == []


def test_generated_catalog_scale():
    cat = generate_catalog()
    assert len(cat) >= 180  # 6 families x 3 generations x ~10 sizes
    names = {s.name for s in cat}
    assert len(names) == len(cat)  # unique
    arm = [s for s in cat if s.arch == "arm64"]
    assert arm and all(s.od_price > 0 for s in cat)


def test_queue(cloud):
    cloud.send_message({"kind": "spot-interruption", "instance_id": "i-1"})
    msgs = cloud.receive_messages()
    assert len(msgs) == 1
    cloud.delete_message(msgs[0])
    assert cloud.receive_messages() == []
