"""Doc-rot guard for the tensor/oracle routing spec (VERDICT r5 item 7).

ops/tensorize.py's module docstring enumerates which constraint shapes
route to the pure-Python oracle.  That list rotted once already: it kept
claiming preference-differing co-location closures go to the oracle
after the compile-time relaxation ladder learned to compile them.  This
suite greps the docstring's oracle-shape list AND probes the router
(class_unsupported_reason / partition_groups) for each listed shape, so
the spec and the code can only change together.
"""

import re

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.api.requirements import Op, Requirement
from karpenter_tpu.ops import tensorize
from karpenter_tpu.ops.tensorize import class_unsupported_reason, partition_groups


def _oracle_sentence() -> str:
    """The docstring sentence that enumerates oracle-routed shapes."""
    doc = tensorize.__doc__
    m = re.search(r"Anything else —(.*?)— is reported", doc, re.DOTALL)
    assert m, "routing docstring lost its oracle-shape list sentence"
    return " ".join(m.group(1).split())


def _coloc(prefs=(), terms=None):
    """One member of a mutual hostname co-location closure."""
    pod = Pod(
        labels={"app": "g"},
        requests=Resources(cpu=1),
        pod_affinity=[
            PodAffinityTerm(
                topology_key=L.LABEL_HOSTNAME, label_selector=(("app", "g"),)
            )
        ],
        preferred_affinity=list(prefs),
    )
    if terms is not None:
        pod.affinity_terms = terms
    return pod


class TestDocstringMatchesRouter:
    def test_oracle_list_names_the_routed_shapes(self):
        sentence = _oracle_sentence()
        for shape in (
            "one-sided cross-class couplings",
            "zone-affinity+spread combos",
            "exotic topology keys",
            "live-member co-location",
            "OR-terms",
        ):
            assert shape in sentence, (shape, sentence)

    def test_oracle_list_does_not_claim_preference_closures(self):
        """The round-5 rot: preference-differing closures COMPILE now
        (member preferences merge as required into ANDed rows, peeled by
        the compile-time ladder) — the oracle list must not claim them."""
        sentence = _oracle_sentence()
        assert "preferences" not in sentence, sentence
        # and the docstring documents the compiled behavior explicitly
        assert "differ only in PREFERENCES compile" in " ".join(
            tensorize.__doc__.split()
        )

    # -- behavior probes: one per listed oracle shape ----------------------

    def test_exotic_topology_key_routes_to_oracle(self):
        pod = Pod(
            labels={"app": "x"},
            requests=Resources(cpu=1),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key="rack", label_selector=(("app", "x"),)
                )
            ],
        )
        assert "topology key rack" in class_unsupported_reason(pod)

    def test_zone_affinity_plus_spread_routes_to_oracle(self):
        pod = Pod(
            labels={"app": "y"},
            requests=Resources(cpu=1),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_ZONE, label_selector=(("app", "y"),)
                )
            ],
            topology_spread=[
                TopologySpreadConstraint(
                    1, L.LABEL_ZONE, label_selector=(("app", "y"),)
                )
            ],
        )
        reason = class_unsupported_reason(pod)
        assert "zone affinity combined" in reason

    def test_one_sided_coupling_routes_to_oracle(self):
        """An anti-affinity selector reaching OTHER pods (not its own
        class) is a one-sided cross-class coupling."""
        attacker = Pod(
            labels={"app": "attacker"},
            requests=Resources(cpu=1),
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_HOSTNAME,
                    anti=True,
                    label_selector=(("app", "victim"),),
                )
            ],
        )
        victim = Pod(labels={"app": "victim"}, requests=Resources(cpu=2))
        groups, unsupported, why = partition_groups([attacker, victim])
        assert unsupported, "one-sided coupling stayed on the tensor path"
        assert why

    def test_preference_differing_closure_compiles(self):
        """The shape the stale comment mis-routed: same OR-terms and
        namespace, different preference lists — merges into ONE macro
        unit on the tensor path."""
        a = _coloc(prefs=[Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])])
        b = _coloc()
        groups, unsupported, why = partition_groups([a, b])
        assert not unsupported, why
        assert len(groups) == 1, "closure should merge into one macro unit"

    def test_or_term_differing_closure_routes_to_oracle(self):
        a = _coloc()
        b = _coloc(
            terms=[
                (Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"]),),
                (),
            ]
        )
        groups, unsupported, why = partition_groups([a, b])
        assert unsupported, "OR-term-differing closure must keep the oracle"

    def test_reason_strings_exist_in_router_source(self):
        """Every docstring-listed shape corresponds to a live code path:
        the reason strings the probes hit are produced by
        class_unsupported_reason / the partition passes, not leftovers."""
        import inspect

        src = inspect.getsource(tensorize)
        for snippet in (
            "pod affinity on topology key",
            "zone affinity combined with another zone constraint",
            "topology spread on key",
        ):
            assert snippet in src, snippet