"""Fleet chaos proof (docs/designs/store-scale.md acceptance): 3 real
Operators + a read replica + a deliberately wedged watcher against ONE
store server, through seeded churn and a scripted failover storm — with
zero double-launches, clean invariants, and byte-identical run/run and
run/replay traces.
"""

import json
import logging

import pytest

from karpenter_tpu.sim.fleet import (
    FLEET_SCENARIOS,
    FleetRunner,
    _FleetTrace,
    read_fleet_tape,
    replay_fleet,
    run_fleet,
)

TICKS = 36


@pytest.fixture(scope="module")
def fleet_run():
    logging.disable(logging.WARNING)  # straggler-fence conflicts are loud
    try:
        runner, report = run_fleet("store-fleet-chaos", 0, TICKS)
    finally:
        logging.disable(logging.NOTSET)
    return runner, report


class TestFleetChaos:
    def test_zero_double_launches_and_clean_invariants(self, fleet_run):
        _runner, report = fleet_run
        assert report["double_launches"] == 0
        assert report["invariants"]["violations"] == []
        assert report["launches"] > 0
        assert report["operators"] == 3

    def test_failover_storm_rotated_leadership(self, fleet_run):
        _runner, report = fleet_run
        # the scripted storm (crash, rejoin, release, second crash)
        # must have moved the lease across replicas
        assert len(report["replicas_led"]) >= 2
        assert report["leader_transitions"] >= 2
        # two writers in one round only across scripted handoffs —
        # anything wider is a single-writer violation (checked per tick)
        assert report["writers_max_per_tick"] <= 2

    def test_store_plane_facts(self, fleet_run):
        _runner, report = fleet_run
        store = report["store"]
        # every operator negotiated the binary codec
        assert store["codec"] == ["bin1"]
        # churn blew past the 64-event replay log: compaction is LIVE in
        # this scenario, and healthy mirrors stayed synced through it
        assert store["replay_log_compactions"] >= 1
        # the wedged watcher overflowed its bounded queue and was
        # coalesced (not OOMed, not head-of-line blocking the healthy)
        assert store["slow_watcher_overflowed"] is True

    def test_read_replica_converged_with_rv_ordering(self, fleet_run):
        _runner, report = fleet_run
        assert report["replica"]["synced"] is True
        assert report["replica"]["rv_ordering_preserved"] is True
        assert report["replica"]["reader_synced"] is True

    def test_run_run_byte_identical(self, fleet_run):
        runner, report = fleet_run
        logging.disable(logging.WARNING)
        try:
            runner2, report2 = run_fleet("store-fleet-chaos", 0, TICKS)
        finally:
            logging.disable(logging.NOTSET)
        assert report2 == report
        assert runner2.trace.text() == runner.trace.text()

    def test_replay_byte_identical(self, fleet_run, tmp_path):
        runner, report = fleet_run
        path = tmp_path / "fleet.jsonl"
        path.write_text(runner.trace.text())
        logging.disable(logging.WARNING)
        try:
            runner3, report3, recorded = replay_fleet(str(path))
        finally:
            logging.disable(logging.NOTSET)
        assert recorded == report
        assert report3 == report
        assert runner3.trace.text() == runner.trace.text()

    def test_trace_structure(self, fleet_run):
        runner, _report = fleet_run
        lines = [
            json.loads(line) for line in runner.trace.text().splitlines()
        ]
        kinds = {l["t"] for l in lines}
        assert {"meta", "tick", "ev", "dig", "fleet", "report"} <= kinds
        meta = lines[0]
        assert meta["fleet"] is True and meta["operators"] == 3
        # every chaos decision was resolved onto the tape (no rng in
        # replay): crash events name their victim
        crashes = [
            l for l in lines if l["t"] == "ev" and l["kind"] == "op_crash"
        ]
        assert crashes and all(l["data"]["replica"] for l in crashes)

    def test_tape_reader_rejects_non_fleet_traces(self, tmp_path):
        p = tmp_path / "not-fleet.jsonl"
        p.write_text('{"t": "meta", "scenario": "steady"}\n')
        with pytest.raises(ValueError, match="not a fleet trace"):
            read_fleet_tape(str(p))

    def test_unknown_scenario_refused(self):
        with pytest.raises(ValueError, match="unknown fleet scenario"):
            FleetRunner("no-such-fleet")

    def test_scenario_registered(self):
        assert "store-fleet-chaos" in FLEET_SCENARIOS
