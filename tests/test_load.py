"""Load-harness suite (karpenter_tpu/load/): the counter RNG's
scalar/vector bit-parity, columnar tapes replaying byte-identical to
their per-event twins, vector-vs-scalar invariant cross-validation
(clean runs AND forged corruptions caught by both planes), the
production scenario corpus with its scale anchors and settle budgets,
the fleet-level report section, and the quantile sketch underneath it."""

import math

import numpy as np
import pytest

from karpenter_tpu.api import labels as L
from karpenter_tpu.load.generators import (
    CDiurnal,
    CInterruptionStorm,
    CPodBurst,
    CScript,
    CSteady,
    EventTape,
    draw_u01,
    draws_u01,
    poisson_icdf,
)
from karpenter_tpu.load.invariants import VectorInvariantChecker
from karpenter_tpu.load.sketch import QuantileSketch
from karpenter_tpu.sim.invariants import (
    GANG_LABEL,
    GANG_SIZE_LABEL,
    InvariantChecker,
)
from karpenter_tpu.sim.report import wall_profile
from karpenter_tpu.sim.runner import (
    Scenario,
    ScenarioRunner,
    replay,
    run_scenario,
)
from karpenter_tpu.sim.trace import TraceWriter
from karpenter_tpu.sim.workload import SimEvent
from karpenter_tpu.state.kube import Node, NodeClaim, Pod


# ------------------------------------------------------------- counter rng
def test_counter_rng_scalar_vector_bit_parity():
    """`draws_u01` must produce the exact bits of `draw_u01` for the same
    counters — the foundation of the tape/twin parity contract."""
    ticks = np.arange(0, 997, 7, dtype=np.int64)
    idxs = (ticks * 13 + 5) % 101
    for seed in (0, 1, 23, 987654321):
        for stream in (0, 3, 17):
            vec = draws_u01(seed, stream, ticks, idxs)
            scalar = [
                draw_u01(seed, stream, int(t), int(i))
                for t, i in zip(ticks, idxs)
            ]
            assert vec.tolist() == scalar  # bit-identical, not approx
            assert float(vec.min()) >= 0.0 and float(vec.max()) < 1.0
    # distinct counters decorrelate: no two coordinates alias
    assert draw_u01(1, 0, 0, 0) != draw_u01(0, 1, 0, 0)
    assert draw_u01(0, 0, 1, 0) != draw_u01(0, 0, 0, 1)


def test_poisson_icdf_is_a_pure_function():
    assert poisson_icdf(0.0, 0.99) == 0
    assert poisson_icdf(-1.0, 0.5) == 0
    assert poisson_icdf(3.0, 0.25) == poisson_icdf(3.0, 0.25)
    # monotone in u, and the empirical mean tracks lambda
    us = [draw_u01(7, 0, t, 0) for t in range(4000)]
    draws = [poisson_icdf(2.5, u) for u in us]
    assert all(k >= 0 for k in draws)
    assert abs(sum(draws) / len(draws) - 2.5) < 0.15
    assert poisson_icdf(2.5, 0.1) <= poisson_icdf(2.5, 0.9)
    # a u deep in the float64 tail terminates (the capped CDF walk)
    assert poisson_icdf(1.0, 1.0 - 2.0**-53) > 0


# --------------------------------------------------------- tape/twin parity
# one spec list per family; factories because twins are stateful
PARITY_FAMILIES = {
    "steady-lifetime": lambda: [
        CSteady(rate=1.2, lifetime=(2, 5), prefix="sl")
    ],
    "diurnal": lambda: [
        CDiurnal(mean=0.9, amplitude=0.8, period_ticks=20, prefix="di")
    ],
    "interruption-storm": lambda: [
        CSteady(rate=0.8, prefix="st"),
        CInterruptionStorm(start=8, duration=6, per_tick=2),
    ],
    "burst-script": lambda: [
        CScript(
            {
                3: [("az_down", {"zone": "zone-b"})],
                12: [("az_up", {"zone": "zone-b"})],
            }
        ),
        CPodBurst(total=10, per_tick=4, start=2, cpu=1.0, mem_gib=2.0),
    ],
}


@pytest.mark.sim
@pytest.mark.parametrize("family", sorted(PARITY_FAMILIES))
def test_tape_replays_byte_identical_to_per_event_twin(family):
    """The tentpole parity contract: a columnar tape drives a scenario to
    the exact bytes its per-event twin generators produce on the same
    seed — arrival counts, shapes, lifetimes, storm target selection."""
    specs = PARITY_FAMILIES[family]
    seed, ticks = 11, 30
    assert EventTape(seed, ticks, specs()).total_events() > 0
    w_tape = TraceWriter()
    tape_scn = Scenario(
        f"parity-{family}",
        tape_factory=lambda s, t: EventTape(s, t, specs()),
    )
    r_tape = ScenarioRunner(tape_scn, seed, ticks, trace=w_tape).run()
    w_twin = TraceWriter()
    twin_scn = Scenario(
        f"parity-{family}", workloads=EventTape(seed, ticks, specs()).twins()
    )
    r_twin = ScenarioRunner(twin_scn, seed, ticks, trace=w_twin).run()
    assert w_tape.text() == w_twin.text()
    assert w_tape.sha256() == w_twin.sha256()
    assert r_tape == r_twin
    assert r_tape["invariants"]["violations"] == []


def test_tape_digest_identity_and_sensitivity():
    def mk(seed, rate=1.0):
        return EventTape(
            seed,
            25,
            [
                CSteady(rate=rate, lifetime=(2, 4), prefix="dg"),
                CInterruptionStorm(start=5, duration=3),
            ],
        )

    assert mk(7).digest() == mk(7).digest()  # build is deterministic
    assert mk(7).digest() != mk(8).digest()  # seed is in the columns
    assert mk(7).digest() != mk(7, rate=1.5).digest()  # params too
    assert mk(7).total_events() == mk(7).total_events()


def test_tape_lifetime_deletes_match_creates():
    """Every delete a lifetimed tape schedules names a pod a prior tick
    created, and never lands beyond the scripted horizon."""
    tape = EventTape(3, 40, [CSteady(rate=2.0, lifetime=(1, 6), prefix="lf")])

    class _View:
        def claimed_instance_ids(self):
            return []

    created, deleted = set(), []
    for t in range(40):
        for ev in tape.materialize(t, _View()):
            if ev.kind == "pod_create":
                created.add(f"default/{ev.data['name']}")
            elif ev.kind == "pod_delete":
                deleted.append(ev.data["key"])
    assert deleted and len(deleted) == len(set(deleted))
    assert set(deleted) <= created
    assert tape.total_events() == len(created) + len(deleted)


# ----------------------------------------------- vector-vs-scalar invariants
@pytest.mark.sim
def test_vector_invariants_cross_validate_clean_run():
    """The same tape-driven scenario checked on the scalar and the
    vectorized invariant planes produces byte-identical traces and equal
    reports — the vector plane changes COST, never outcomes."""

    def specs():
        return [
            CSteady(rate=1.0, lifetime=(3, 6), prefix="xv"),
            CInterruptionStorm(start=10, duration=4, per_tick=1),
        ]

    def scn(vec):
        return Scenario(
            "xval",
            tape_factory=lambda s, t: EventTape(s, t, specs()),
            vector_invariants=vec,
        )

    w_scalar = TraceWriter()
    run_scalar = ScenarioRunner(scn(False), 9, 35, trace=w_scalar)
    r_scalar = run_scalar.run()
    w_vector = TraceWriter()
    run_vector = ScenarioRunner(scn(True), 9, 35, trace=w_vector)
    r_vector = run_vector.run()
    assert not isinstance(run_scalar.checker, VectorInvariantChecker)
    assert isinstance(run_vector.checker, VectorInvariantChecker)
    assert w_scalar.text() == w_vector.text()
    assert r_scalar == r_vector
    assert r_scalar["invariants"]["violations"] == []
    reg_v = run_vector.env.registry
    reg_s = run_scalar.env.registry
    assert reg_v.counter("karpenter_load_vector_checked_ticks_total") > 0
    assert reg_s.counter("karpenter_load_vector_checked_ticks_total") == 0


@pytest.mark.sim
def test_forged_corruptions_caught_by_both_planes():
    """Forged state corruptions — a double-launched claim and a ghost
    node — must be caught by BOTH invariant planes with byte-identical
    violation strings (the cross-validation teeth)."""
    runner, report = run_scenario("steady", seed=13, ticks=30)
    assert report["invariants"]["violations"] == []
    env = runner.env
    claims = [
        c
        for c in env.kube.node_claims.values()
        if c.provider_id and c.deleted_at is None
    ]
    assert claims, "steady run must leave live claims to forge against"
    env.kube.node_claims["forged"] = NodeClaim(
        name="forged", pool_name="default", provider_id=claims[0].provider_id
    )
    env.kube.nodes["ghost"] = Node(name="ghost", provider_id="i-never-was")
    scalar = InvariantChecker(env)
    vector = VectorInvariantChecker(env)
    scalar.check_tick(0)
    vector.check_tick(0)
    sv = [(v.invariant, v.detail) for v in scalar.violations]
    vv = [(v.invariant, v.detail) for v in vector.violations]
    assert sv == vv  # identical strings, identical order
    kinds = {k for k, _ in sv}
    assert "no-double-launch" in kinds
    assert "registered-eq-launched" in kinds


@pytest.mark.sim
def test_gang_atomicity_clean_and_partial_on_both_planes():
    """A gang with zero members placed is fine; a PARTIAL gang trips
    gang-atomic identically on both planes."""
    runner, _ = run_scenario("steady", seed=5, ticks=20)
    env = runner.env
    assert env.kube.nodes, "need a node to bind a partial gang onto"
    scalar = InvariantChecker(env)
    vector = VectorInvariantChecker(env)
    gang = {GANG_LABEL: "forged-slice", GANG_SIZE_LABEL: "3"}
    for i in range(3):  # the watch feeds both checkers' gang mirrors
        env.kube.put_pod(Pod(name=f"g-{i}", labels=dict(gang)))
    scalar.check_tick(0)
    vector.check_tick(0)
    assert [v.invariant for v in scalar.violations] == []
    assert [v.invariant for v in vector.violations] == []
    env.kube.bind_pod("default/g-0", next(iter(env.kube.nodes)))
    scalar.check_tick(1)
    vector.check_tick(1)
    sv = [(v.invariant, v.detail) for v in scalar.violations]
    vv = [(v.invariant, v.detail) for v in vector.violations]
    assert sv == vv
    assert (
        "gang-atomic",
        "gang forged-slice: 1/3 members placed (slices land all-or-nothing)",
    ) in sv


# ---------------------------------------------------------------- corpus
@pytest.mark.sim
def test_anchor_antiaffinity_smoke_shape():
    """Tier-1 shape of the 500-node BASELINE anchor: every anti-affine
    pod forces its own node, inside the settle budget."""
    runner, report = run_scenario(
        "anchor-500-antiaffinity-smoke", seed=1, ticks=12
    )
    assert report["invariants"]["violations"] == []
    kube = runner.env.kube
    assert runner.pods_created == 24
    assert len(kube.nodes) == 24  # one node per hostile pod
    for name in kube.nodes:
        assert len(kube.pods_on_node(name)) == 1
    fleet = report["fleet"]
    assert fleet["settle_budget_s"] == 600.0
    assert 0.0 <= fleet["time_to_settle_s"] <= 600.0


@pytest.mark.sim
def test_anchor_density_smoke_shape():
    """Tier-1 shape of the 6,600-pod anchor: `max_pods=110` is the
    binding constraint, so 220 tiny pods pack onto exactly 2 nodes."""
    runner, report = run_scenario(
        "anchor-6600-density-smoke", seed=1, ticks=12
    )
    assert report["invariants"]["violations"] == []
    kube = runner.env.kube
    assert runner.pods_created == 220
    assert len(kube.nodes) == 2
    for name in kube.nodes:
        assert len(kube.pods_on_node(name)) == 110  # slot-packed full
    assert 0.0 <= report["fleet"]["time_to_settle_s"] <= 600.0


@pytest.mark.slow
@pytest.mark.sim
def test_anchor_500_antiaffinity_full():
    """The full BASELINE anchor: 500 anti-affine pods -> 500 single-pod
    nodes inside the 30-minute settle budget."""
    runner, report = run_scenario("anchor-500-antiaffinity", seed=1, ticks=15)
    assert report["invariants"]["violations"] == []
    kube = runner.env.kube
    assert runner.pods_created == 500
    assert len(kube.nodes) == 500
    assert 0.0 <= report["fleet"]["time_to_settle_s"] <= 1800.0


@pytest.mark.slow
@pytest.mark.sim
def test_anchor_6600_density_full():
    """The full density anchor: 6,600 pods at 110 pods/node -> 60 dense
    nodes inside the 30-minute settle budget."""
    runner, report = run_scenario("anchor-6600-density", seed=1, ticks=15)
    assert report["invariants"]["violations"] == []
    kube = runner.env.kube
    assert runner.pods_created == 6600
    assert len(kube.nodes) == 60
    for name in kube.nodes:
        assert len(kube.pods_on_node(name)) == 110
    assert 0.0 <= report["fleet"]["time_to_settle_s"] <= 1800.0


@pytest.mark.sim
def test_gang_slice_lands_atomically_in_one_zone():
    """The multi-host slice gang lands all-or-nothing: 8 members on 8
    distinct hosts, all in the one zone left standing mid-drought."""
    runner, report = run_scenario("gang-slice", seed=3, ticks=30)
    assert report["invariants"]["violations"] == []
    kube = runner.env.kube
    members = [
        p
        for p in kube.pods.values()
        if p.labels.get(GANG_LABEL) == "slice-a"
    ]
    assert len(members) == 8
    assert all(p.node_name for p in members)  # the whole slice landed
    hosts = {p.node_name for p in members}
    assert len(hosts) == 8  # hostname anti-affinity: one member per host
    zones = {kube.nodes[h].labels.get(L.LABEL_ZONE) for h in hosts}
    assert len(zones) == 1  # zone co-location across the slice


_T1_CORPUS = [
    ("anchor-500-antiaffinity-smoke", 12),
    ("anchor-6600-density-smoke", 12),
    ("gang-slice", 30),
    ("spot-shock-drought", 32),
    ("catalog-deprecations", 30),
]


@pytest.mark.sim
@pytest.mark.parametrize("name,ticks", _T1_CORPUS)
def test_corpus_scenarios_byte_identical_and_replayable(
    name, ticks, tmp_path
):
    """Every corpus scenario is byte-identical run/run AND run/replay,
    report included."""
    path = str(tmp_path / f"{name}.jsonl")
    w1 = TraceWriter(path)
    _, r1 = run_scenario(name, seed=7, ticks=ticks, trace=w1)
    assert r1["invariants"]["violations"] == []
    w2 = TraceWriter()
    _, r2 = run_scenario(name, seed=7, ticks=ticks, trace=w2)
    assert w2.text() == open(path).read()
    assert r1 == r2
    w3 = TraceWriter()
    _, replayed, recorded = replay(path, trace=w3)
    assert recorded == r1
    assert replayed == r1
    assert w3.text() == open(path).read()


@pytest.mark.slow
@pytest.mark.sim
def test_million_events_byte_identical_and_replayable(tmp_path):
    """The throughput anchor on the vector plane: run/run and run/replay
    byte-identity at a few hundred events per tick."""
    path = str(tmp_path / "million.jsonl")
    w1 = TraceWriter(path)
    runner1, r1 = run_scenario("million-events", seed=23, ticks=60, trace=w1)
    assert r1["invariants"]["violations"] == []
    reg = runner1.env.registry
    assert reg.counter("karpenter_load_vector_checked_ticks_total") > 0
    assert sum(runner1.event_counts.values()) > 50_000
    w2 = TraceWriter()
    _, r2 = run_scenario("million-events", seed=23, ticks=60, trace=w2)
    assert w2.text() == open(path).read()
    assert r1 == r2
    _, replayed, recorded = replay(path)
    assert recorded == r1
    assert replayed == r1


def test_cli_lists_corpus_scenarios(capsys):
    from karpenter_tpu.sim.cli import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in (
        "anchor-500-antiaffinity",
        "anchor-500-antiaffinity-smoke",
        "anchor-6600-density",
        "anchor-6600-density-smoke",
        "gang-slice",
        "spot-shock-drought",
        "catalog-deprecations",
        "million-events",
    ):
        assert name in out


# ------------------------------------------------------------ corpus events
@pytest.mark.sim
def test_price_shock_event_scales_spot_cells():
    runner = ScenarioRunner(Scenario("evt"), seed=1, ticks=1)
    cloud = runner.env.cloud
    types = sorted(cloud.shapes)
    zones = list(cloud.zones)
    before = {
        (t, z): cloud.spot_price(t, z) for t in types for z in zones
    }
    runner.apply_event(SimEvent("price_shock", {"factor": 4.0}))
    for (t, z), p in before.items():
        assert cloud.spot_price(t, z) == pytest.approx(p * 4.0)
    # selective shock: only the named (type, zone) cell moves
    t0, z0 = types[0], zones[0]
    shocked = {(t, z): cloud.spot_price(t, z) for t in types for z in zones}
    runner.apply_event(
        SimEvent(
            "price_shock",
            {"factor": 0.5, "instance_type": t0, "zone": z0},
        )
    )
    for (t, z), p in shocked.items():
        want = p * 0.5 if (t, z) == (t0, z0) else p
        assert cloud.spot_price(t, z) == pytest.approx(want)
    assert runner.event_counts["price_shock"] == 2


@pytest.mark.sim
def test_image_deprecate_event_moves_resolution():
    runner = ScenarioRunner(Scenario("evt"), seed=1, ticks=1)
    env = runner.env
    runner.apply_event(
        SimEvent("image_roll", {"id": "image-standard-amd64-v2"})
    )
    assert "image-standard-amd64-v2" in env.cloud.images
    runner.apply_event(
        SimEvent("image_deprecate", {"id": "image-standard-amd64"})
    )
    assert env.cloud.images["image-standard-amd64"].deprecated
    # deprecating an unknown id is a no-op, not a crash
    runner.apply_event(SimEvent("image_deprecate", {"id": "nope"}))
    assert runner.event_counts["image_deprecate"] == 2


# ------------------------------------------------------------ fleet report
@pytest.mark.sim
def test_fleet_section_and_phase_profile():
    runner, report = run_scenario("steady", seed=2, ticks=25)
    fleet = report["fleet"]
    assert set(fleet) == {
        "tts",
        "pod_hours",
        "cost_per_pod_hour",
        "disruptions_per_hour",
        "time_to_settle_s",
        "settle_budget_s",
    }
    tts = fleet["tts"]
    assert set(tts) == {"count", "p50", "p99", "p999", "max"}
    assert tts["count"] == runner.tts_sketch.count > 0
    assert 0.0 <= tts["p50"] <= tts["p99"] <= tts["p999"] <= tts["max"]
    assert fleet["pod_hours"] > 0.0
    assert fleet["cost_per_pod_hour"] > 0.0
    assert fleet["disruptions_per_hour"] >= 0.0
    assert fleet["settle_budget_s"] is None  # steady declares no budget
    assert fleet["time_to_settle_s"] >= 0.0
    # the --profile phase split: per-tick wall broken into the harness
    # phases, with the harness-overhead fraction alongside
    prof = wall_profile(runner.env.registry)
    phases = prof["sim_phases"]
    assert {"generate", "apply", "reconcile", "invariants"} <= set(phases)
    for split in phases.values():
        assert split["count"] > 0
        assert split["total_s"] >= 0.0
        assert split["p50_s"] >= 0.0
    assert 0.0 <= prof["harness_fraction"] < 1.0


def test_quantile_sketch_accuracy_merge_and_zeros():
    values = [((i * 2654435761) % 10_000) * 0.013 + 0.01 for i in range(2000)]
    sk = QuantileSketch()
    for v in values:
        sk.observe(v)
    ordered = sorted(values)
    for q in (0.5, 0.9, 0.99, 0.999):
        rank = max(0, min(len(ordered) - 1, int(round(q * (len(ordered) - 1)))))
        exact = ordered[rank]
        assert abs(sk.quantile(q) - exact) <= 0.016 * exact + 1e-9
    assert sk.vmax == max(values)
    assert abs(sk.quantile(1.0) - sk.vmax) <= 0.016 * sk.vmax
    # merge is exactly a sketch over the union (order-free)
    a, b, whole = QuantileSketch(), QuantileSketch(), QuantileSketch()
    for i, v in enumerate(values):
        (a if i % 2 else b).observe(v)
        whole.observe(v)
    a.merge(b)
    assert a.section() == whole.section()
    # zeros own their bucket: an idle fleet's p50 is exactly 0.0
    z = QuantileSketch()
    for _ in range(10):
        z.observe(0.0)
    z.observe(5.0)
    assert z.quantile(0.5) == 0.0
    assert z.section()["max"] == 5.0
    assert z.count == 11
    # empty sketch is all zeros
    assert QuantileSketch().section() == {
        "count": 0, "p50": 0.0, "p99": 0.0, "p999": 0.0, "max": 0.0,
    }
    # bucket resolution bound: ~0.8% relative error per octave bucket
    one = QuantileSketch()
    one.observe(3.7)
    assert abs(one.quantile(0.99) - 3.7) <= 0.016 * 3.7
    assert math.isfinite(one.quantile(0.5))
