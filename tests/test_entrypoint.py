"""Controller entry point + the 3-layer settings resolution (reference
cmd/controller/main.go:33-70; website v0.31 settings.md:15-27)."""

import json
import subprocess
import sys

import pytest

from karpenter_tpu.api import Settings


class TestSettingsLayers:
    def test_from_env(self):
        s = Settings.from_env(
            {
                "KARPENTER_CLUSTER_NAME": "prod",
                "KARPENTER_ISOLATED_VPC": "true",
                "KARPENTER_VM_MEMORY_OVERHEAD_PERCENT": "0.05",
                "KARPENTER_TAGS": '{"team": "ml"}',
                "KARPENTER_INTERRUPTION_QUEUE_NAME": "q1",
            }
        )
        assert s.cluster_name == "prod"
        assert s.isolated_vpc is True
        assert s.vm_memory_overhead_percent == 0.05
        assert s.tags == {"team": "ml"}
        assert s.interruption_queue_name == "q1"
        s.validate()

    def test_from_file(self, tmp_path):
        p = tmp_path / "settings.json"
        p.write_text(json.dumps({"cluster_name": "file-cluster"}))
        s = Settings.from_file(str(p))
        assert s.cluster_name == "file-cluster"

    def test_from_file_rejects_unknown(self, tmp_path):
        p = tmp_path / "settings.json"
        p.write_text(json.dumps({"cluster_name": "x", "bogus": 1}))
        with pytest.raises(ValueError, match="bogus"):
            Settings.from_file(str(p))


class TestEntryPoint:
    def test_dump_settings(self, tmp_path):
        p = tmp_path / "settings.json"
        p.write_text(json.dumps({"cluster_name": "smoke"}))
        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "karpenter_tpu",
                "--settings-file",
                str(p),
                "--dump-settings",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert json.loads(out.stdout)["cluster_name"] == "smoke"

    def test_controller_runs_and_serves_metrics(self, tmp_path):
        """Boot the real controller process, hit /healthz + /metrics,
        SIGTERM it down."""
        import signal
        import time
        import urllib.request

        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "karpenter_tpu",
                "--interval",
                "0.05",
                "--metrics-port",
                "18123",
            ],
            env={"KARPENTER_CLUSTER_NAME": "e2e", "PATH": "/usr/bin:/bin"},
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            deadline = time.time() + 60
            body = ""
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                        "http://127.0.0.1:18123/metrics", timeout=2
                    ) as resp:
                        body = resp.read().decode()
                    if "karpenter_controller_reconcile_total" in body:
                        break
                except OSError:
                    time.sleep(0.3)
            assert "karpenter_controller_reconcile_total" in body, body[:500]
            with urllib.request.urlopen(
                "http://127.0.0.1:18123/healthz", timeout=2
            ) as resp:
                assert resp.read() == b"ok"
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


class TestDeviceObservatoryEndToEnd:
    def test_debug_device_after_warm_ticks(self):
        """Boot the real controller process with the bundled demo
        workload, scrape /debug/device, and assert the device-layer
        acceptance criteria: after the process has reconciled past its
        second tick, resident device-buffer bytes are live (> 0 — the
        demo pods' solve seeded the resident tensors) and NO jit entry
        point recompiled on a warm tick (warm ticks replay cached
        programs; a warm compile means a padded bucket churned)."""
        import json as _json
        import signal
        import time
        import urllib.request

        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "karpenter_tpu",
                "--interval",
                "0.05",
                "--metrics-port",
                "18127",
                "--demo-pods",
                "24",
            ],
            env={
                "KARPENTER_CLUSTER_NAME": "e2e-device",
                "PATH": "/usr/bin:/bin",
                "JAX_PLATFORMS": "cpu",
            },
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            deadline = time.time() + 120
            snap = {}
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                        "http://127.0.0.1:18127/debug/device", timeout=2
                    ) as resp:
                        snap = _json.loads(resp.read().decode())
                except (OSError, ValueError):
                    time.sleep(0.3)
                    continue
                # wait until the process is PAST its second tick and has
                # actually dispatched device work for the demo solve
                if (
                    snap.get("tick", 0) >= 3
                    and sum(snap.get("dispatches", {}).values()) > 0
                ):
                    break
                time.sleep(0.3)
            assert snap.get("tick", 0) >= 3, snap
            resident = snap.get("resident", {})
            assert resident.get("bytes_total", 0) > 0, snap
            assert sum(snap.get("compiles", {}).values()) > 0, snap
            assert snap.get("warm_recompiles", {}) == {}, snap
            assert sum(snap.get("transfer_bytes", {}).values()) > 0, snap
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


class TestSharedStoreEndToEnd:
    def test_store_server_and_two_controllers(self):
        """The full HA shape as real processes: `python -m karpenter_tpu
        store-server` plus two `--store-address` controllers.  Exactly one
        controller leads; SIGTERM-ing it releases the Lease and the
        standby takes over (the chart's replicas: 2 + store.enabled
        deployment, in miniature)."""
        import signal
        import socket
        import time
        import urllib.request

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        store_port, m1, m2 = free_port(), free_port(), free_port()
        env = {"KARPENTER_CLUSTER_NAME": "e2e-ha", "PATH": "/usr/bin:/bin"}

        def controller(metrics_port):
            return subprocess.Popen(
                [
                    sys.executable, "-m", "karpenter_tpu",
                    "--interval", "0.05",
                    "--metrics-port", str(metrics_port),
                    "--store-address", f"127.0.0.1:{store_port}",
                ],
                env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )

        def leading(metrics_port):
            """parse karpenter_leader_election_leading off /metrics."""
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{metrics_port}/metrics", timeout=2
                ) as resp:
                    body = resp.read().decode()
            except OSError:
                return None
            for line in body.splitlines():
                if line.startswith("karpenter_leader_election_leading"):
                    return line.rsplit(" ", 1)[1] == "1"
            return None

        store = subprocess.Popen(
            [
                sys.executable, "-m", "karpenter_tpu", "store-server",
                "--port", str(store_port),
            ],
            env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        procs = [store]
        try:
            # wait for the store socket before starting controllers
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    socket.create_connection(
                        ("127.0.0.1", store_port), timeout=1
                    ).close()
                    break
                except OSError:
                    time.sleep(0.3)
            else:
                raise AssertionError(
                    f"store-server never listened: {store.stderr.read()[:800]}"
                )

            c1, c2 = controller(m1), controller(m2)
            procs += [c1, c2]
            # exactly one leader between the two replicas
            deadline = time.time() + 90
            states = (None, None)
            while time.time() < deadline:
                states = (leading(m1), leading(m2))
                if sorted(filter(lambda s: s is not None, states)) == [
                    False, True,
                ]:
                    break
                time.sleep(0.5)
            assert sorted(
                s for s in states if s is not None
            ) == [False, True], (
                states,
                c1.poll() and c1.stderr.read()[:500],
                c2.poll() and c2.stderr.read()[:500],
            )
            leader, standby = (
                (c1, m2) if states[0] else (c2, m1)
            )
            # graceful failover: SIGTERM releases the Lease; the standby
            # must take over well inside the 15s lease duration
            leader.send_signal(signal.SIGTERM)
            leader.wait(timeout=30)
            deadline = time.time() + 60
            took_over = False
            while time.time() < deadline:
                if leading(standby):
                    took_over = True
                    break
                time.sleep(0.5)
            assert took_over, "standby never took over after SIGTERM handoff"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)


class TestPreflight:
    def test_empty_catalog_fails_fast(self):
        """Reference operator.go:190-200 dry-runs DescribeInstanceTypes at
        startup; an unreachable/empty cloud must fail Operator
        construction with an actionable error, not the first reconcile."""
        from karpenter_tpu.cloud.fake.backend import FakeCloud
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.state.kube import KubeStore
        from karpenter_tpu.utils.clock import FakeClock

        cloud = FakeCloud(FakeClock(), shapes=()).with_default_topology()
        with pytest.raises(RuntimeError, match="preflight"):
            Operator(cloud, KubeStore())
