"""Mesh-sharded solve must produce identical results to the single-device
kernel (conftest pins an 8-device virtual CPU platform)."""

import jax
import numpy as np
import pytest

from karpenter_tpu.ops.packer import pack_kernel
from karpenter_tpu.parallel.mesh import (
    assemble_feasibility,
    make_mesh,
    sharded_solve_step,
)


@pytest.fixture(scope="module")
def problem():
    S, T, Z, CT, R = 4, 8, 2, 2, 6
    C = T * Z * CT * 2  # 64, divisible by the model axis
    G, K = 16, 32
    rng = np.random.RandomState(42)
    return dict(
        type_ok=rng.rand(S, T) < 0.8,
        zone_ok=np.ones((S, Z), bool),
        ct_ok=np.ones((S, CT), bool),
        sig_of=(np.arange(G) % S).astype(np.int32),
        t_of=(np.arange(C) % T).astype(np.int32),
        z_of=((np.arange(C) // T) % Z).astype(np.int32),
        ct_of=((np.arange(C) // (T * Z)) % CT).astype(np.int32),
        req=(np.abs(rng.rand(G, R)) + 0.1).astype(np.float32),
        cnt=np.full(G, 5, np.int32),
        maxper=np.full(G, 2**20, np.int32),
        slot=np.zeros(G, np.int32),
        alloc=(np.abs(rng.rand(C, R)) * 16 + 8).astype(np.float32),
        price=(rng.rand(C) + 0.5).astype(np.float32),
        openable=np.ones(C, bool),
        used0=np.zeros((K, R), np.float32),
        cfg0=np.full(K, -1, np.int32),
        npods0=np.zeros(K, np.int32),
        next0=np.int32(0),
        sig0=np.zeros((5, K), np.int32),
    )


def test_mesh_shape():
    mesh = make_mesh(8)
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("data", "model")


def test_sharded_matches_single_device(problem):
    p = problem
    K = p["used0"].shape[0]
    feas = np.asarray(
        assemble_feasibility(
            p["type_ok"], p["zone_ok"], p["ct_ok"],
            p["sig_of"], p["t_of"], p["z_of"], p["ct_of"],
        )
    )
    single = pack_kernel(
        p["req"], p["cnt"], p["maxper"], p["slot"], feas,
        p["alloc"], p["price"], p["openable"],
        p["used0"], p["cfg0"], p["npods0"], p["next0"], p["sig0"],
        k_slots=K,
    )
    mesh = make_mesh(8)
    step = sharded_solve_step(mesh, k_slots=K)
    sharded = step(
        p["type_ok"], p["zone_ok"], p["ct_ok"],
        p["sig_of"], p["t_of"], p["z_of"], p["ct_of"],
        p["req"], p["cnt"], p["maxper"], p["slot"],
        p["alloc"], p["price"], p["openable"],
        p["used0"], p["cfg0"], p["npods0"], p["next0"], p["sig0"],
    )
    jax.block_until_ready(sharded)
    np.testing.assert_array_equal(np.asarray(single.take), np.asarray(sharded.take))
    np.testing.assert_array_equal(
        np.asarray(single.node_cfg), np.asarray(sharded.node_cfg)
    )
    np.testing.assert_array_equal(
        np.asarray(single.leftover), np.asarray(sharded.leftover)
    )
    np.testing.assert_allclose(
        np.asarray(single.node_used), np.asarray(sharded.node_used), rtol=1e-5
    )


def test_mesh_pack_fn_flagship_scale_through_scheduler():
    """The production multi-chip path (TensorScheduler with the
    mesh-sharded pack_fn) must decode identical placements to the
    single-device default at bench scale — the flagship 10k-pod x
    ~500-type problem sharded over the 8-device CPU mesh."""
    import bench
    from karpenter_tpu.parallel.mesh import mesh_pack_fn
    from karpenter_tpu.scheduling.solver import TensorScheduler

    pool, types, pods = bench.build_problem()

    single = TensorScheduler([pool], {pool.name: types}).solve(pods)
    sharded = TensorScheduler(
        [pool], {pool.name: types}, pack_fn=mesh_pack_fn(make_mesh(8))
    ).solve(pods)

    assert not single.unschedulable and not sharded.unschedulable
    assert len(single.new_nodes) == len(sharded.new_nodes)
    s_sizes = sorted(
        (len(n.pods), n.feasible_types[0].name) for n in single.new_nodes
    )
    m_sizes = sorted(
        (len(n.pods), n.feasible_types[0].name) for n in sharded.new_nodes
    )
    assert s_sizes == m_sizes


def test_all_pods_placed_or_leftover(problem):
    p = problem
    K = p["used0"].shape[0]
    feas = np.asarray(
        assemble_feasibility(
            p["type_ok"], p["zone_ok"], p["ct_ok"],
            p["sig_of"], p["t_of"], p["z_of"], p["ct_of"],
        )
    )
    out = pack_kernel(
        p["req"], p["cnt"], p["maxper"], p["slot"], feas,
        p["alloc"], p["price"], p["openable"],
        p["used0"], p["cfg0"], p["npods0"], p["next0"], p["sig0"],
        k_slots=K,
    )
    total = int(np.asarray(out.take).sum()) + int(np.asarray(out.leftover).sum())
    assert total == int(p["cnt"].sum())
    # no node slot overcommitted on any resource axis
    cfg = np.asarray(out.node_cfg)
    used = np.asarray(out.node_used)
    for k in range(K):
        if cfg[k] >= 0:
            assert (used[k] <= p["alloc"][cfg[k]] + 1e-3).all()


def test_sharded_deep_class_axis_parity():
    """A DEEP sequential G axis (hundreds of scan steps over varied
    constrained classes) sharded==single: where a bug in the scan-carried
    slot state or the per-class collectives would actually hide — the
    driver's dry run asserts the same on the full config-2 problem."""
    import jax

    from karpenter_tpu.api import Pod, Resources
    from karpenter_tpu.ops.packer import run_pack
    from karpenter_tpu.ops.tensorize import compile_problem
    from karpenter_tpu.parallel.mesh import mesh_pack_fn
    from karpenter_tpu.testing import Environment

    env = Environment()
    nc = env.default_node_class()
    pool = env.default_node_pool()
    types = env.instance_types.list(pool, nc)
    pods = []
    for i in range(600):  # ~200 (cpu, memory) request classes
        cpu = 0.25 * (1 + i % 40)
        mem = f"{1 + (i // 40) % 5}Gi"
        pods.append(Pod(requests=Resources(cpu=cpu, memory=mem)))
    prob = compile_problem(pods, [pool], {pool.name: types})
    G = len(prob.classes)
    assert G >= 150, G

    mesh = make_mesh(8)
    single = run_pack(prob)
    sharded = mesh_pack_fn(mesh)(prob)
    take_s = np.asarray(jax.device_get(single.take))
    take_m = np.asarray(jax.device_get(sharded.take))
    ks = min(take_s.shape[1], take_m.shape[1])
    np.testing.assert_array_equal(take_s[:G, :ks], take_m[:G, :ks])
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(single.leftover))[:G],
        np.asarray(jax.device_get(sharded.leftover))[:G],
    )
