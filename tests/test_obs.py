"""Observability plane (docs/designs/observability.md): trace-context
propagation (operator tick -> spans -> ledger -> store RPC -> server
span), the typed cluster event ledger, cumulative histogram buckets +
the window-proof percentiles, the Prometheus exposition / telemetry
endpoint, and the Chrome-trace renderer."""

import http.client
import json
import threading

import pytest

from karpenter_tpu.api import Pod, Resources, Settings
from karpenter_tpu.cloud.fake.backend import CloudAPIError, FakeCloud, MachineShape
from karpenter_tpu.cloud.retry import RetryingCloud
from karpenter_tpu.metrics.registry import (
    BUCKET_BOUNDS,
    Registry,
    exposition,
)
from karpenter_tpu.obs.context import (
    current_trace_id,
    mint_trace_id,
    set_tick,
    trace_context,
)
from karpenter_tpu.obs.events import EventLedger
from karpenter_tpu.obs.http import start_telemetry
from karpenter_tpu.obs.render import (
    chrome_from_sim_trace,
    chrome_from_spans,
    self_times,
    top_table,
)
from karpenter_tpu.testing import Environment
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.trace import TRACER, Tracer


@pytest.fixture(autouse=True)
def _clean_tick():
    """Tests that mint tick IDs must not leak them into each other."""
    set_tick("")
    yield
    set_tick("")


# ---------------------------------------------------------------- context
class TestTraceContext:
    def test_mint_is_deterministic(self):
        assert mint_trace_id(1) == "tick-000001"
        assert mint_trace_id(42, "op-a") == "op-a-000042"

    def test_tick_default_and_thread_local_override(self):
        set_tick("tick-000007")
        assert current_trace_id() == "tick-000007"
        with trace_context("client-000003"):
            assert current_trace_id() == "client-000003"
            with trace_context(""):  # empty override keeps the outer one
                assert current_trace_id() == "client-000003"
        assert current_trace_id() == "tick-000007"

    def test_worker_threads_inherit_the_tick_default(self):
        set_tick("tick-000009")
        seen = {}

        def worker():
            seen["tid"] = current_trace_id()

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert seen["tid"] == "tick-000009"

    def test_override_is_thread_local(self):
        set_tick("tick-000001")
        seen = {}
        installed = threading.Event()
        release = threading.Event()

        def worker():
            installed.wait(1.0)
            seen["tid"] = current_trace_id()
            release.set()

        t = threading.Thread(target=worker)
        t.start()
        with trace_context("other-000001"):
            installed.set()
            release.wait(1.0)
        t.join()
        assert seen["tid"] == "tick-000001"  # override never crossed threads


# ----------------------------------------------------------------- ledger
class TestEventLedger:
    def test_emit_stamps_clock_seq_and_trace_id(self):
        clock = FakeClock()
        reg = Registry()
        led = EventLedger(clock=clock, registry=reg)
        set_tick("tick-000004")
        clock.step(5.0)
        ev = led.emit("NodeDisrupted", node="n-1", reason="expired")
        assert ev.seq == 1
        assert ev.ts == clock.now()
        assert ev.trace_id == "tick-000004"
        assert ev.attrs == {"node": "n-1", "reason": "expired"}
        assert reg.counter(
            "karpenter_events_total", {"type": "NodeDisrupted"}
        ) == 1

    def test_ring_bounded_and_drain(self):
        led = EventLedger(clock=FakeClock(), capacity=8)
        for i in range(20):
            led.emit("PodNominated", pod=f"p-{i}")
        assert len(led.recent()) == 8
        assert led.recent()[-1].seq == 20
        fresh = led.drain(since_seq=15)
        assert [ev.seq for ev in fresh] == [16, 17, 18, 19, 20]
        assert led.counts() == {"PodNominated": 8}

    def test_read_reports_dropped_and_respects_limit(self):
        """The cursor satellite's ledger half: a reader whose cursor fell
        behind the ring sees HOW MANY events it lost, and a limit pages
        from the old end so catching up never skips."""
        led = EventLedger(clock=FakeClock(), capacity=8)
        for i in range(20):
            led.emit("PodNominated", pod=f"p-{i}")
        events, dropped = led.read(0)
        assert [ev.seq for ev in events] == list(range(13, 21))
        assert dropped == 12
        events, dropped = led.read(15, limit=3)
        assert [ev.seq for ev in events] == [16, 17, 18]
        assert dropped == 0
        events, dropped = led.read(20)
        assert events == [] and dropped == 0

    def test_jsonl_sink(self, tmp_path):
        path = tmp_path / "events.jsonl"
        led = EventLedger(clock=FakeClock(), sink_path=str(path))
        led.emit("CircuitOpen", api="create_fleet")
        led.close()
        (line,) = path.read_text().splitlines()
        obj = json.loads(line)
        assert obj["type"] == "CircuitOpen"
        assert obj["attrs"] == {"api": "create_fleet"}

    def test_registry_event_without_ledger_is_noop(self):
        Registry().event("NodeLaunched", claim="x")  # must not raise

    def test_span_records_current_trace_id(self):
        t = Tracer(enabled=True)
        set_tick("tick-000011")
        with t.span("controller.provisioner"):
            pass
        (span,) = t.recent()
        assert span.trace_id == "tick-000011"


# --------------------------------------------------- histogram percentiles
class TestHistogramBuckets:
    def test_exact_path_matches_report_percentile_below_window(self):
        from karpenter_tpu.sim.report import percentile

        reg = Registry()
        samples = [((i * 37) % 100) / 100.0 for i in range(500)]
        for v in samples:
            reg.observe("karpenter_pods_time_to_schedule_seconds", v)
        for q in (0.5, 0.95, 0.99):
            assert reg.quantile(
                "karpenter_pods_time_to_schedule_seconds", q
            ) == percentile(samples, q)

    def test_percentiles_stay_honest_past_the_window(self):
        """The r06 regression pinned: _Hist keeps a 1024-sample window,
        so past 1024 observations a window percentile describes only the
        TAIL of the run.  9000 slow observations followed by 1024 fast
        ones: the window says p99 = 0.01s, the truth is ~1s — the bucket
        estimate must stay near the truth."""
        from karpenter_tpu.sim.report import percentile

        reg = Registry()
        name = "karpenter_provisioner_scheduling_duration_seconds"
        for _ in range(9000):
            reg.observe(name, 1.0)
        for _ in range(1024):
            reg.observe(name, 0.01)
        degraded = percentile(reg.histogram(name), 0.99)
        assert degraded == 0.01  # the silent lie this satellite fixes
        honest = reg.quantile(name, 0.99)
        assert 0.9 <= honest <= 1.0

    def test_overflow_bucket_returns_tracked_max(self):
        reg = Registry()
        for _ in range(2000):
            reg.observe("karpenter_nodes_termination_time_seconds", 5000.0)
        assert reg.quantile(
            "karpenter_nodes_termination_time_seconds", 0.99
        ) == 5000.0

    def test_bucket_counts_are_cumulative_in_exposition(self):
        reg = Registry()
        reg.observe("karpenter_solver_phase_seconds", 0.003, {"phase": "pad"})
        reg.observe("karpenter_solver_phase_seconds", 0.3, {"phase": "pad"})
        text = exposition(reg)
        lines = [
            l for l in text.splitlines()
            if l.startswith("karpenter_solver_phase_seconds_bucket")
        ]
        def at(le):
            return [l for l in lines if f'le="{le}"' in l][0]

        # cumulative: every bound >= 0.5 sees both observations
        assert at("0.0025").endswith(" 0")
        assert at("0.005").endswith(" 1")
        assert at("0.5").endswith(" 2")
        assert at("+Inf").endswith(" 2")


# ------------------------------------------------------------- exposition
class TestExposition:
    def test_help_type_and_series_lines(self):
        reg = Registry()
        reg.inc("karpenter_events_total", {"type": "NodeLaunched"})
        reg.set("karpenter_leader_election_leading", 1.0, {"identity": "a"})
        text = exposition(reg)
        assert "# TYPE karpenter_events_total counter" in text
        assert "# HELP karpenter_events_total" in text
        # the catalog's description rides the HELP line
        assert "cluster event ledger entries" in text
        assert "# TYPE karpenter_leader_election_leading gauge" in text
        # the e2e suite parses `name{...} 1` off this surface
        assert 'karpenter_leader_election_leading{identity="a"} 1' in text

    def test_label_values_escaped(self):
        reg = Registry()
        reg.inc("karpenter_events_total", {"type": 'we"ird\nvalue'})
        text = exposition(reg)
        assert '\\"' in text and "\\n" in text

    def test_large_counter_values_keep_full_precision(self):
        """%g would render 1_234_567 as 1.23457e+06 on the wire — a
        corrupted absolute value for any real Prometheus server."""
        reg = Registry()
        reg.inc("karpenter_events_total", {"type": "t"}, by=1234567)
        reg.observe("karpenter_batcher_batch_size", 1234567.25)
        text = exposition(reg)
        assert 'karpenter_events_total{type="t"} 1234567' in text
        assert "karpenter_batcher_batch_size_sum 1234567.25" in text
        assert "e+06" not in text

    def test_histogram_sum_count(self):
        reg = Registry()
        reg.observe("karpenter_batcher_batch_size", 3.0)
        reg.observe("karpenter_batcher_batch_size", 7.0)
        text = exposition(reg)
        assert "karpenter_batcher_batch_size_sum 10" in text
        assert "karpenter_batcher_batch_size_count 2" in text
        assert "# TYPE karpenter_batcher_batch_size histogram" in text


# ------------------------------------------------------ telemetry endpoint
def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class TestTelemetryEndpoint:
    @pytest.fixture()
    def served(self):
        reg = Registry()
        tracer = Tracer(enabled=True)
        led = EventLedger(clock=FakeClock(), registry=reg)
        reg.ledger = led
        reg.inc("karpenter_controller_reconcile_total", {"controller": "x"})
        led.emit("NodeLaunched", claim="nc-1", pool="default")
        with tracer.span("controller.provisioner"):
            pass
        server = start_telemetry(0, reg, tracer=tracer, ledger=led,
                                 host="127.0.0.1")
        yield server.server_address[1], reg
        server.shutdown()

    def test_metrics_endpoint_serves_exposition(self, served):
        port, reg = served
        status, body = _get(port, "/metrics")
        assert status == 200
        text = body.decode()
        assert "# TYPE karpenter_controller_reconcile_total counter" in text
        assert "karpenter_events_total" in text
        # the endpoint counts its own scrapes (visible from scrape 1)
        assert 'karpenter_telemetry_scrapes_total{endpoint="metrics"} 1' in text

    def test_healthz_events_trace_and_404(self, served):
        port, reg = served
        assert _get(port, "/healthz") == (200, b"ok")
        status, body = _get(port, "/events")
        assert status == 200
        payload = json.loads(body)
        assert payload["events"][0]["type"] == "NodeLaunched"
        assert payload["events"][0]["attrs"]["claim"] == "nc-1"
        assert payload["last_seq"] == payload["events"][-1]["seq"]
        assert payload["dropped"] == 0
        assert payload["ring_counts"] == {"NodeLaunched": 1}
        assert payload["total_counts"] == {"NodeLaunched": 1}
        status, body = _get(port, "/trace")
        assert status == 200
        payload = json.loads(body)
        assert payload["stats"]["controller.provisioner"]["count"] == 1
        assert payload["recent"][0]["path"] == "controller.provisioner"
        status, _ = _get(port, "/nope")
        assert status == 404
        assert reg.counter(
            "karpenter_telemetry_scrapes_total", {"endpoint": "events"}
        ) == 1

    def test_events_cursor_pages_forward(self, served):
        """The /events?since_seq=N&limit=M satellite: a poller pages
        forward from its cursor without re-reading (or silently missing)
        events."""
        port, reg = served
        led = reg.ledger
        for i in range(5):
            led.emit("PodNominated", pod=f"p-{i}")
        status, body = _get(port, "/events?since_seq=1&limit=2")
        assert status == 200
        payload = json.loads(body)
        seqs = [ev["seq"] for ev in payload["events"]]
        assert seqs == [2, 3]  # oldest first, capped by limit
        assert payload["last_seq"] == 3
        assert payload["dropped"] == 0
        # next page picks up exactly where the cursor left off
        payload2 = json.loads(_get(port, "/events?since_seq=3&limit=100")[1])
        assert [ev["seq"] for ev in payload2["events"]] == [4, 5, 6]

    def test_events_overflow_reports_dropped_and_both_counts(self):
        """The counts-ambiguity satellite, endpoint-level: overflow the
        ring and check that ring_counts (bounded) and total_counts
        (cumulative) diverge honestly and dropped is reported."""
        reg = Registry()
        led = EventLedger(clock=FakeClock(), registry=reg, capacity=8)
        reg.ledger = led
        for i in range(20):
            led.emit("PodNominated", pod=f"p-{i}")
        server = start_telemetry(0, reg, ledger=led, host="127.0.0.1")
        try:
            port = server.server_address[1]
            payload = json.loads(_get(port, "/events?since_seq=0")[1])
            # ring holds the last 8 (seqs 13..20); 12 aged out unread
            assert [ev["seq"] for ev in payload["events"]] == list(
                range(13, 21)
            )
            assert payload["dropped"] == 12
            assert payload["ring_counts"] == {"PodNominated": 8}
            assert payload["total_counts"] == {"PodNominated": 20}
            # a bare GET (no cursor) serves the NEWEST events — a human
            # curl on a full ring must show what just happened, not the
            # oldest survivors
            bare = json.loads(_get(port, "/events?limit=3")[1])
            assert [ev["seq"] for ev in bare["events"]] == [18, 19, 20]
            assert bare["dropped"] == 0
        finally:
            server.shutdown()


# --------------------------------------------------- concurrent scrapes
def _assert_exposition_parses(text: str) -> None:
    """Every non-comment line must be `name[{labels}] value` with a
    finite float value — the contract a real Prometheus scraper needs."""
    import re as _re

    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        assert head and _re.match(
            r"^[a-z_][a-zA-Z0-9_]*(\{.*\})?$", head
        ), f"unparseable exposition line: {line!r}"
        float(value)


class TestConcurrentScrapes:
    def test_hammer_telemetry_while_operator_ticks(self):
        """The satellite: threaded scrapes of /metrics + /events (+ the
        flight ring) while the operator reconciles pods — no exceptions
        on either side, and every exposition snapshot parses."""
        from karpenter_tpu.obs.flight import read_flight

        env = Environment()
        env.default_node_class()
        env.default_node_pool()
        server = start_telemetry(
            0, env.registry,
            tracer=env.operator.tracer,
            ledger=env.operator.ledger,
            flight=env.operator.flight,
            host="127.0.0.1",
        )
        port = server.server_address[1]
        errors = []
        stop = threading.Event()

        def hammer(path):
            while not stop.is_set():
                try:
                    status, body = _get(port, path)
                    assert status == 200, (path, status)
                    if path == "/metrics":
                        _assert_exposition_parses(body.decode())
                    elif path == "/events":
                        json.loads(body)
                    elif body:
                        read_flight(body.decode())
                except Exception as exc:  # noqa: BLE001 (collected)
                    errors.append((path, repr(exc)))
                    return

        threads = [
            threading.Thread(target=hammer, args=(p,), daemon=True)
            for p in ("/metrics", "/events", "/metrics", "/debug/flight")
        ]
        for t in threads:
            t.start()
        try:
            for i in range(12):
                env.kube.put_pod(
                    Pod(requests=Resources(cpu=1, memory="1Gi"))
                )
                env.step(2.0)
        finally:
            stop.set()
            for t in threads:
                t.join(5.0)
            server.shutdown()
        assert not errors, errors
        scrapes = env.registry.counter(
            "karpenter_telemetry_scrapes_total", {"endpoint": "metrics"}
        )
        assert scrapes > 0


# -------------------------------------------- decision-site ledger events
def _retrying():
    clock = FakeClock()
    cloud = FakeCloud(
        clock,
        shapes=[MachineShape(name="std1.large", cpu=4, memory=16 * 2**30)],
        zones=["zone-a"],
    ).with_default_topology()
    reg = Registry()
    reg.ledger = EventLedger(clock=clock, registry=reg)
    retrying = RetryingCloud(
        cloud, clock=clock,
        settings=Settings(cluster_name="t", cloud_backoff_base=0.01,
                          cloud_backoff_max=0.1,
                          cloud_circuit_failure_threshold=2),
        registry=reg,
    )
    return clock, cloud, reg, retrying


class TestLedgerDecisionSites:
    def test_retry_backoff_event_carries_tick_trace_id(self):
        clock, cloud, reg, retrying = _retrying()
        set_tick("tick-000123")
        cloud.recorder.set_error_sequence(
            "DescribeInstances", [CloudAPIError("InternalError")]
        )
        retrying.describe_instances()
        (ev,) = [e for e in reg.ledger.recent() if e.type == "RetryBackoff"]
        assert ev.trace_id == "tick-000123"
        assert ev.attrs["api"] == "describe_instances"
        assert ev.attrs["classification"] == "transient"
        assert float(ev.attrs["backoff_s"]) >= 0.0

    def test_circuit_open_event(self):
        clock, cloud, reg, retrying = _retrying()
        cloud.recorder.set_error_sequence(
            "DescribeSubnets", [CloudAPIError("InternalError")] * 10
        )
        with pytest.raises(CloudAPIError):
            retrying.describe_subnets([])
        opens = [e for e in reg.ledger.recent() if e.type == "CircuitOpen"]
        assert len(opens) == 1
        assert opens[0].attrs["api"] == "describe_subnets"

    def test_stale_served_event(self):
        from karpenter_tpu.providers.stale import StaleGuard

        clock = FakeClock()
        reg = Registry()
        reg.ledger = EventLedger(clock=clock, registry=reg)
        guard = StaleGuard("pricing", clock, registry=reg)
        guard.fetch("k", lambda: 42)
        clock.step(30.0)

        def boom():
            raise CloudAPIError("ServiceUnavailable")

        value, fresh = guard.fetch("k", boom)
        assert value == 42 and not fresh
        (ev,) = [e for e in reg.ledger.recent() if e.type == "StaleServed"]
        assert ev.attrs["provider"] == "pricing"
        assert float(ev.attrs["age_s"]) == pytest.approx(30.0)


# ----------------------------------------------- one tick, one trace ID
class TestTickCorrelation:
    def test_nomination_launch_and_solver_spans_share_the_tick_id(self):
        """The acceptance chain, in-process: a pending pod's nomination,
        its NodeClaim's launch, and the solver's spans all carry the
        trace ID the operator minted for that tick."""
        env = Environment(
            settings=Settings(cluster_name="test", enable_profiling=True)
        )
        TRACER.reset()
        try:
            env.default_node_class()
            env.default_node_pool()
            env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="1Gi")))
            env.settle()
            events = env.operator.ledger.recent()
            nominated = [e for e in events if e.type == "PodNominated"]
            launched = [e for e in events if e.type == "NodeLaunched"]
            assert nominated and launched
            tid = launched[0].trace_id
            assert tid.startswith("tick-") and tid == nominated[0].trace_id
            # the same tick's solver spans carry the same ID
            solver_spans = [
                s for s in TRACER.recent(4096)
                if s.path.startswith("controller.provisioner")
                and s.trace_id == tid
            ]
            assert solver_spans
            assert env.registry.counter(
                "karpenter_events_total", {"type": "PodNominated"}
            ) >= 1
        finally:
            TRACER.enabled = False
            TRACER.profile_dir = ""
            TRACER.reset()

    def test_disruption_reason_reaches_the_ledger(self):
        env = Environment()
        env.default_node_class()
        pool = env.default_node_pool()
        pool.disruption.expire_after = 60.0
        env.kube.put_node_pool(pool)
        env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="1Gi")))
        env.settle()
        env.step(120.0)  # everything on the node is past expire_after
        env.step(1.0)
        disrupted = [
            e for e in env.operator.ledger.recent()
            if e.type == "NodeDisrupted"
        ]
        assert disrupted
        assert any(e.attrs["reason"] == "expired" for e in disrupted)


# ------------------------------------- store RPC trace-context propagation
class TestStorePropagation:
    def test_server_span_carries_client_trace_id(self):
        """The two-process chain: a RemoteKubeStore write issued under a
        tick's trace context lands in the StoreServer's span log UNDER
        THAT trace ID — one timeline across the socket."""
        from karpenter_tpu.service.store_server import StoreServer
        from karpenter_tpu.state.remote import RemoteKubeStore

        srv = StoreServer().start_background()
        host, port = srv.address
        kube = RemoteKubeStore(host, port, identity="op-a")
        try:
            set_tick("op-a-000017")
            kube.put_pod(Pod(name="traced-pod",
                             requests=Resources(cpu=1, memory="1Gi")))
            spans = [
                s for s in srv.tracer.recent(200) if s.path == "store.put"
            ]
            assert spans, [s.path for s in srv.tracer.recent(200)]
            assert spans[-1].trace_id == "op-a-000017"
            assert srv.registry.counter(
                "karpenter_store_requests_total", {"method": "put"}
            ) >= 1
            # the client's background watch is counted and spanned too —
            # it registers ASYNCHRONOUSLY on its own thread, so under a
            # loaded CPU the RPC may land after the put: poll briefly
            # instead of racing it
            import time as _time

            deadline = _time.monotonic() + 10.0
            while (
                srv.registry.counter(
                    "karpenter_store_requests_total", {"method": "watch"}
                )
                < 1
                and _time.monotonic() < deadline
            ):
                _time.sleep(0.02)
            assert srv.registry.counter(
                "karpenter_store_requests_total", {"method": "watch"}
            ) >= 1
            assert any(
                s.path == "store.watch" for s in srv.tracer.recent(200)
            )
        finally:
            kube.close()
            srv.stop()

    def test_rpcs_without_context_record_untraced_spans(self):
        from karpenter_tpu.service.store_server import StoreServer
        from karpenter_tpu.state.remote import RemoteKubeStore

        srv = StoreServer().start_background()
        host, port = srv.address
        kube = RemoteKubeStore(host, port, identity="op-b")
        try:
            set_tick("")
            kube.put_pod(Pod(name="untraced-pod",
                             requests=Resources(cpu=1, memory="1Gi")))
            spans = [
                s for s in srv.tracer.recent(200) if s.path == "store.put"
            ]
            assert spans and spans[-1].trace_id == ""
        finally:
            kube.close()
            srv.stop()


# ---------------------------------------------------------------- renderer
class TestRenderer:
    def test_chrome_from_spans(self):
        payload = {
            "stats": {"tick": {"count": 1, "total_s": 0.5, "max_s": 0.5},
                      "tick.solve": {"count": 1, "total_s": 0.3,
                                     "max_s": 0.3}},
            "recent": [
                {"path": "tick", "start_s": 10.0, "duration_s": 0.5,
                 "trace_id": "tick-000001", "meta": {}},
                {"path": "tick.solve", "start_s": 10.1, "duration_s": 0.3,
                 "trace_id": "tick-000001", "meta": {"pods": "7"}},
            ],
        }
        chrome = chrome_from_spans(payload)
        durations = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
        assert len(durations) == 2
        assert durations[0]["ts"] == 0.0  # normalized to the earliest span
        assert durations[1]["args"]["trace_id"] == "tick-000001"
        # both spans share one trace ID -> one timeline row
        assert durations[0]["tid"] == durations[1]["tid"]

    def test_self_time_table(self):
        stats = {
            "tick": {"count": 2, "total_s": 1.0, "max_s": 0.6},
            "tick.solve": {"count": 2, "total_s": 0.8, "max_s": 0.5},
            "tick.solve.pack": {"count": 2, "total_s": 0.3, "max_s": 0.2},
        }
        rows = {path: self_s for path, self_s, _ in self_times(stats)}
        assert rows["tick"] == pytest.approx(0.2)
        assert rows["tick.solve"] == pytest.approx(0.5)
        assert rows["tick.solve.pack"] == pytest.approx(0.3)
        # self-time descending: tick.solve (0.5) > tick.solve.pack (0.3)
        # > tick (0.2); n=2 keeps the first two rows
        rows_out = top_table(stats, n=2).splitlines()
        assert len(rows_out) == 3  # header + 2 rows
        assert rows_out[1].startswith("tick.solve ")
        assert rows_out[2].startswith("tick.solve.pack ")

    def test_chrome_from_sim_trace_lines(self):
        lines = [
            {"t": "meta", "scenario": "steady", "seed": 0, "ticks": 2,
             "tick_s": 1.0},
            {"t": "tick", "tick": 0, "dt": 1.0, "phase": "run"},
            {"t": "ev", "tick": 0, "kind": "pod_create",
             "data": {"name": "p-0"}},
            {"t": "led", "tick": 0, "seq": 1, "ts": 100.5,
             "type": "PodNominated", "trace_id": "tick-000001",
             "attrs": {"pod": "default/p-0"}},
            {"t": "dig", "tick": 0, "now": 101.0, "pods": 1, "pending": 0,
             "nodes": 1, "claims": 1, "running": 1, "sha": "x"},
            {"t": "tick", "tick": 1, "dt": 1.0, "phase": "run"},
            {"t": "dig", "tick": 1, "now": 102.0, "pods": 1, "pending": 0,
             "nodes": 1, "claims": 1, "running": 1, "sha": "x"},
        ]
        chrome = chrome_from_sim_trace(lines)
        events = chrome["traceEvents"]
        ticks = [e for e in events if e["ph"] == "X"]
        assert len(ticks) == 2 and ticks[0]["dur"] == 1.0e6
        led = [e for e in events if e["ph"] == "i" and e["tid"] == 3]
        assert led[0]["name"] == "PodNominated"
        assert led[0]["args"]["trace_id"] == "tick-000001"
        # the ledger instant lands INSIDE tick 0 (ts 0.5s of 0..1s)
        assert 0.0 <= led[0]["ts"] <= 1.0e6
        counters = [e for e in events if e["ph"] == "C"]
        assert counters


# ------------------------------------------- sim ledger: trace + replay
@pytest.mark.sim
class TestSimLedger:
    def test_trace_records_ledger_and_report_counts_it(self, tmp_path):
        from karpenter_tpu.sim.runner import run_scenario
        from karpenter_tpu.sim.trace import TraceWriter, read_trace

        path = tmp_path / "t.jsonl"
        _, report = run_scenario(
            "interruption-storm", seed=1, ticks=40,
            trace=TraceWriter(str(path)),
        )
        led = [l for l in read_trace(str(path)) if l["t"] == "led"]
        assert led, "no ledger lines recorded"
        counts = report["cluster_events"]["counts"]
        assert counts.get("PodNominated", 0) > 0
        assert counts.get("NodeLaunched", 0) > 0
        # the trace's led lines and the report section agree
        from collections import Counter

        assert counts == dict(Counter(l["type"] for l in led))
        # the storm disrupted nodes, and the reasons are attributed
        reasons = report["cluster_events"]["disruptions_by_reason"]
        assert sum(reasons.values()) == counts.get("NodeDisrupted", 0)
        assert any(r.startswith("interruption/") for r in reasons)
        # every led line carries a deterministic tick trace ID
        assert all(l["trace_id"].startswith("tick-") for l in led)

    def test_ledger_byte_identical_across_run_and_replay(self, tmp_path):
        """The determinism satellite: the led lines are part of the
        byte-comparable trace surface — equal seeds AND a tape replay
        reproduce them exactly."""
        from karpenter_tpu.sim.runner import replay, run_scenario
        from karpenter_tpu.sim.trace import TraceWriter, read_trace

        p1, p2, p3 = (tmp_path / f"t{i}.jsonl" for i in range(3))
        _, r1 = run_scenario(
            "interruption-storm", seed=3, ticks=30, trace=TraceWriter(str(p1))
        )
        _, r2 = run_scenario(
            "interruption-storm", seed=3, ticks=30, trace=TraceWriter(str(p2))
        )
        assert p1.read_text() == p2.read_text()
        _, r3, recorded = replay(str(p1), trace=TraceWriter(str(p3)))
        assert recorded == r3 == r1

        def led(path):
            return [l for l in read_trace(str(path)) if l["t"] == "led"]

        assert led(p1) == led(p3) and led(p1)

    def test_obs_cli_renders_a_recorded_sim_run(self, tmp_path, capsys):
        """Acceptance: `python -m karpenter_tpu obs` emits Perfetto-
        loadable Chrome-trace JSON from a recorded sim run."""
        from karpenter_tpu.__main__ import main as cli_main
        from karpenter_tpu.sim.runner import run_scenario
        from karpenter_tpu.sim.trace import TraceWriter

        path = tmp_path / "run.jsonl"
        run_scenario("steady", seed=0, ticks=20, trace=TraceWriter(str(path)))
        out = tmp_path / "run.chrome.json"
        rc = cli_main(["obs", str(path), "--out", str(out)])
        assert rc == 0
        chrome = json.loads(out.read_text())
        events = chrome["traceEvents"]
        assert events
        phases = {e["ph"] for e in events}
        assert {"X", "M"} <= phases  # durations + track names
        assert any(
            e.get("args", {}).get("trace_id", "").startswith("tick-")
            for e in events
        )
        captured = capsys.readouterr()
        assert "cluster events recorded" in captured.out
