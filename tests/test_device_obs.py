"""Device observatory (obs/device.py): compile/transfer/resident
telemetry for the on-device hot path.

Covers the seam semantics (dispatch counting, signature-based
would-compile accounting, warm-recompile detection, the counted put),
the scope determinism contract the sim report relies on, the registry
export, the solver integration (a resident warm tick uploads NOTHING),
the /debug/device endpoint, the flight `device` section — and the
twin-run guarantee: observatory on vs off changes zero scheduling
actions tick-for-tick.
"""

import json
import urllib.request

import numpy as np
import pytest

from karpenter_tpu.api import Disruption, Pod, Resources, Settings
from karpenter_tpu.api.objects import reset_name_sequences
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.obs.device import (
    OBSERVATORY,
    DeviceObservatory,
    export_device_metrics,
)
from karpenter_tpu.cloud.fake.backend import generate_catalog
from karpenter_tpu.testing import Environment


def _jit(fn):
    import jax

    return jax.jit(fn)


class TestDispatchSeam:
    def test_counts_compiles_transfers_and_dispatches(self):
        obs = DeviceObservatory()
        fn = _jit(lambda x: x + 1)
        obs.begin_tick(1)
        obs.dispatch("f", fn, np.zeros(4, np.float32))
        t = obs.total
        assert t.dispatches["f"] == 1
        assert t.compiles["f"] == 1
        assert t.transfer_bytes["f"] == 16  # 4 x float32
        assert t.warm_recompiles == {}
        assert t.compile_s["f"] > 0.0
        # cache-hot repeat: a dispatch and an upload, no compile
        obs.dispatch("f", fn, np.ones(4, np.float32))
        assert t.dispatches["f"] == 2
        assert t.compiles["f"] == 1
        assert t.transfer_bytes["f"] == 32

    def test_warm_recompile_flags_only_after_first_tick(self):
        obs = DeviceObservatory()
        fn = _jit(lambda x: x * 2)
        obs.begin_tick(1)
        obs.dispatch("f", fn, np.zeros(4, np.float32))
        # a SECOND shape in the same first tick is a cold compile
        obs.dispatch("f", fn, np.zeros(8, np.float32))
        assert obs.total.compiles["f"] == 2
        assert obs.total.warm_recompiles == {}
        # the same shapes on a later tick: cached, nothing counts
        obs.begin_tick(2)
        obs.dispatch("f", fn, np.zeros(8, np.float32))
        assert obs.total.compiles["f"] == 2
        # a FRESH shape on a later tick is the warm-recompile signal
        obs.dispatch("f", fn, np.zeros(16, np.float32))
        assert obs.total.compiles["f"] == 3
        assert obs.total.warm_recompiles["f"] == 1

    def test_device_args_count_zero_transfer(self):
        import jax

        obs = DeviceObservatory()
        fn = _jit(lambda x: x + 1)
        dev = jax.device_put(np.zeros(4, np.float32))
        obs.dispatch("f", fn, dev)
        assert obs.total.transfer_bytes.get("f", 0) == 0

    def test_disabled_observatory_is_a_passthrough(self):
        obs = DeviceObservatory()
        obs.enabled = False
        fn = _jit(lambda x: x + 1)
        out = obs.dispatch("f", fn, np.zeros(4, np.float32))
        assert float(np.asarray(out)[0]) == 1.0
        assert obs.total.dispatches == {}
        obs.put("site", np.zeros(8, np.float32))
        assert obs.total.transfer_bytes == {}

    def test_tick_section_deltas(self):
        obs = DeviceObservatory()
        fn = _jit(lambda x: x - 1)
        obs.begin_tick(1)
        obs.dispatch("f", fn, np.zeros(4, np.float32))
        sec = obs.tick_section()
        assert sec["compiles"] == 1
        assert sec["dispatches"] == 1
        assert sec["transfer_bytes"] == 16
        obs.begin_tick(2)
        sec2 = obs.tick_section()
        assert sec2["compiles"] == 0 and sec2["dispatches"] == 0


class TestPutSeam:
    def test_counts_leaf_bytes_and_returns_device_value(self):
        obs = DeviceObservatory()
        out = obs.put("site", np.zeros(10, np.float32))
        assert int(np.asarray(out).shape[0]) == 10
        assert obs.total.transfer_bytes["site"] == 40
        # tuple pytrees sum their leaves
        obs.put("site", (np.zeros(2, np.float32), np.zeros(3, np.int32)))
        assert obs.total.transfer_bytes["site"] == 40 + 8 + 12


class TestScopes:
    def test_scope_counts_signatures_not_cache_growth(self):
        """The determinism contract: a scope opened AFTER the process
        already compiled a shape still counts it — a second sim run in
        one process must report the same would-compile count as the
        first, or run/run byte-identity dies."""
        obs = DeviceObservatory()
        fn = _jit(lambda x: x + 3)
        obs.dispatch("f", fn, np.zeros(4, np.float32))  # process-warm
        scope = obs.begin_scope()
        obs.dispatch("f", fn, np.zeros(4, np.float32))
        sec = scope.device_section()
        assert sec["compiles"] == {"f": 1}  # distinct signature, counted
        assert obs.total.compiles["f"] == 1  # actual compile: only once
        # repeats dedup within the scope
        obs.dispatch("f", fn, np.zeros(4, np.float32))
        assert scope.device_section()["compiles"] == {"f": 1}
        assert scope.device_section()["dispatches"] == {"f": 3 - 1}
        obs.end_scope(scope)
        obs.dispatch("f", fn, np.zeros(4, np.float32))
        assert scope.device_section()["dispatches"] == {"f": 2}

    def test_section_is_counts_and_bytes_only(self):
        obs = DeviceObservatory()
        fn = _jit(lambda x: x + 4)
        scope = obs.begin_scope()
        obs.dispatch("f", fn, np.zeros(4, np.float32))
        sec = scope.device_section()
        assert set(sec) == {
            "compiles", "dispatches", "transfer_bytes", "resident",
        }
        flat = json.dumps(sec)
        assert "seconds" not in flat and "_s\"" not in flat


class _Owner:
    pass


class TestExport:
    def test_delta_export_and_warm_events(self):
        obs = DeviceObservatory()
        reg = Registry()
        fn = _jit(lambda x: x + 5)
        obs.begin_tick(1)
        obs.dispatch("f", fn, np.zeros(4, np.float32))
        exported, warm = export_device_metrics(reg, obs, None)
        assert warm == []  # first-tick compile is cold
        assert reg.counter(
            "karpenter_device_compiles_total", {"fn": "f"}
        ) == 1
        assert reg.counter(
            "karpenter_device_transfer_bytes_total", {"site": "f"}
        ) == 16
        assert reg.histogram(
            "karpenter_device_compile_seconds", {"fn": "f"}
        )
        # idle re-export: deltas are zero, nothing double-counts
        exported, warm = export_device_metrics(reg, obs, exported)
        assert reg.counter(
            "karpenter_device_compiles_total", {"fn": "f"}
        ) == 1
        # a warm recompile surfaces as an event attribution
        obs.begin_tick(2)
        obs.dispatch("f", fn, np.zeros(8, np.float32))
        exported, warm = export_device_metrics(reg, obs, exported)
        assert len(warm) == 1 and warm[0]["fn"] == "f"
        assert warm[0]["compile_s"] > 0
        assert reg.counter(
            "karpenter_device_warm_recompiles_total", {"fn": "f"}
        ) == 1

    def test_resident_gauge_tracks_and_unsets(self):
        obs = DeviceObservatory()
        reg = Registry()
        owner = _Owner()
        obs.set_resident_footprint(owner, {"solve": 1000})
        exported, _ = export_device_metrics(reg, obs, None)
        assert reg.gauge(
            "karpenter_device_resident_bytes", {"consumer": "solve"}
        ) == 1000.0
        obs.set_resident_footprint(owner, {"removal": 64})
        exported, _ = export_device_metrics(reg, obs, exported)
        assert reg.gauge(
            "karpenter_device_resident_bytes", {"consumer": "solve"}
        ) is None
        assert reg.gauge(
            "karpenter_device_resident_bytes", {"consumer": "removal"}
        ) == 64.0

    def test_multiple_owners_merge(self):
        obs = DeviceObservatory()
        a, b = _Owner(), _Owner()
        obs.set_resident_footprint(a, {"solve": 100})
        obs.set_resident_footprint(b, {"solve": 20, "removal": 7})
        assert obs.resident_footprint() == {"solve": 120, "removal": 7}

    def test_dead_owner_drops_out_without_a_new_report(self):
        """The merge is computed at READ time over the weak dict: a
        collected cache's bytes vanish from the footprint on their own,
        even if no surviving cache ever reports again (steady warm
        clusters never rebuild)."""
        import gc

        obs = DeviceObservatory()
        a, b = _Owner(), _Owner()
        obs.set_resident_footprint(a, {"solve": 100})
        obs.set_resident_footprint(b, {"removal": 7})
        del a
        gc.collect()
        assert obs.resident_footprint() == {"removal": 7}
        assert obs.snapshot()["resident"]["bytes_total"] == 7
        assert obs.tick_section()["resident_bytes"] == 7


class TestSolverIntegration:
    def test_warm_resident_solve_uploads_nothing(self):
        """The warm-tick transfer contract on a real solve: the cold
        solve pays the seed upload, a warm compile-cache hit packs from
        resident buffers and ships ZERO bytes; the resident footprint is
        live and consumer-labeled."""
        from karpenter_tpu.scheduling import TensorScheduler

        env = Environment(
            shapes=generate_catalog(generations=(1, 2), cpus=(4, 8))
        )
        pool = env.default_node_pool()
        nc = env.default_node_class()
        types = env.instance_types.list(pool, nc)
        pods = [
            Pod(requests=Resources(cpu=0.5, memory=2**30))
            for _ in range(40)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        scope = OBSERVATORY.begin_scope()
        try:
            ts.solve(pods)
            cold = scope.device_section()
            assert cold["transfer_bytes"].get("resident_seed", 0) > 0
            assert ts.resident_rebuilds == 1
            before = sum(cold["transfer_bytes"].values())
            ts.solve(pods)  # compile-cache hit -> resident match
            warm = scope.device_section()
            assert ts.last_resident
            assert sum(warm["transfer_bytes"].values()) == before
            assert warm["resident"]["updates"].get("seed") == 1
        finally:
            OBSERVATORY.end_scope(scope)
        fp = ts._resident.footprint()
        assert fp.get("solve", 0) > 0

    def test_warm_delta_counts_donated_update_and_payload_bytes(self):
        from karpenter_tpu.scheduling import TensorScheduler

        env = Environment(
            shapes=generate_catalog(generations=(1, 2), cpus=(4, 8))
        )
        pool = env.default_node_pool()
        nc = env.default_node_class()
        types = env.instance_types.list(pool, nc)
        pods = [
            Pod(requests=Resources(cpu=0.5, memory=2**30))
            for _ in range(24)
        ]
        ts = TensorScheduler([pool], {pool.name: types})
        ts.solve(pods)
        scope = OBSERVATORY.begin_scope()
        try:
            pods2 = pods + [Pod(requests=Resources(cpu=1, memory=2**30))]
            ts.solve(pods2)  # delta tick: one class arrives
            sec = scope.device_section()
            assert ts.last_resident and ts.last_delta_rows > 0
            assert sec["resident"]["updates"].get("donated") == 1
            # the delta shipped only scatter payloads — bytes came from
            # the resident_delta dispatch, not a re-seed
            assert sec["transfer_bytes"].get("resident_delta", 0) > 0
            assert "resident_seed" not in sec["transfer_bytes"]
        finally:
            OBSERVATORY.end_scope(scope)


class TestEndpointAndFlight:
    def test_debug_device_endpoint_serves_snapshot(self):
        from karpenter_tpu.obs.http import start_telemetry

        reg = Registry()
        server = start_telemetry(0, reg, device=OBSERVATORY)
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/device", timeout=5
            ) as resp:
                snap = json.loads(resp.read().decode())
            assert "resident" in snap and "compiles" in snap
            assert snap["enabled"] is True
            assert reg.counter(
                "karpenter_telemetry_scrapes_total",
                {"endpoint": "debug/device"},
            ) == 1
        finally:
            server.shutdown()

    def test_flight_tick_carries_device_section(self):
        env = Environment(
            shapes=generate_catalog(generations=(1, 2), cpus=(4, 8))
        )
        env.default_node_class()
        env.default_node_pool()
        env.kube.put_pod(Pod(requests=Resources(cpu=0.5, memory=2**30)))
        env.settle(max_rounds=10)
        ticks = list(env.operator.flight._ring)
        dev = ticks[-1]["device"]
        assert set(dev) >= {
            "compiles", "warm_recompiles", "dispatches", "transfer_bytes",
            "resident_bytes", "resident_delta_bytes",
        }
        # the pod's solve dispatched on SOME recorded tick
        assert any(t["device"]["dispatches"] > 0 for t in ticks), [
            t["device"] for t in ticks
        ]
        # and the registry families were exported by the diagnosis tail
        assert sum(
            env.registry.counters.get(
                "karpenter_device_dispatches_total", {}
            ).values()
        ) > 0


def _twin_run(enabled: bool):
    """Drive a deterministic provision→churn→consolidate schedule and
    record every tick's full action surface."""
    reset_name_sequences()
    env = Environment(
        shapes=generate_catalog(generations=(1, 2), cpus=(4, 8)),
        settings=Settings(
            cluster_name="twin", enable_device_observatory=enabled
        ),
    )
    env.operator.provisioner.launch_concurrency = 1
    env.default_node_class()
    env.default_node_pool(
        disruption=Disruption(consolidation_policy="WhenUnderutilized")
    )
    pods = [
        Pod(
            name=f"twin-{i}",
            requests=Resources(cpu=0.5 if i % 2 else 1.0, memory=2**30),
        )
        for i in range(30)
    ]
    for p in pods:
        env.kube.put_pod(p)
    actions = []
    for t in range(14):
        if t == 6:
            # mass deletion strands capacity: consolidation must act
            for p in pods[:20]:
                env.kube.delete_pod(p.key())
        env.step(2.0)
        actions.append(
            (
                sorted(env.kube.node_claims),
                sorted(env.kube.nodes),
                sorted(
                    (k, p.node_name) for k, p in env.kube.pods.items()
                ),
                sorted(
                    i.id
                    for i in env.cloud.instances.values()
                    if i.state == "running"
                ),
            )
        )
    return actions


class TestTwinRun:
    def test_observatory_on_off_changes_zero_actions(self):
        """The observatory is accounting, never policy: with it disabled
        the same schedule takes the identical actions tick-for-tick."""
        try:
            on = _twin_run(enabled=True)
            off = _twin_run(enabled=False)
        finally:
            OBSERVATORY.enabled = True
        assert on == off
