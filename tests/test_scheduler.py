"""FFD oracle scheduler semantics (reference designs/bin-packing.md +
website v0.31 concepts/scheduling.md)."""

import pytest

from karpenter_tpu.api import (
    Pod,
    Requirement,
    Resources,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.state.cluster import StateNode
from karpenter_tpu.testing import Environment


@pytest.fixture
def env():
    return Environment()


def make_scheduler(env, pools=None, existing=(), daemonsets=()):
    pools = pools or [env.default_node_pool()]
    env.default_node_class()
    types = {p.name: env.instance_types.list(pool=p) for p in pools}
    return Scheduler(pools, types, existing=existing, daemonsets=daemonsets)


def test_homogeneous_pods_pack_tightly(env):
    s = make_scheduler(env)
    pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(100)]
    result = s.solve(pods)
    assert not result.unschedulable
    # 100 cpu of demand must not open 100 nodes; FFD should pack densely
    assert result.node_count() <= 4
    placed = sum(len(n.pods) for n in result.new_nodes)
    assert placed == 100


def test_pod_too_big_unschedulable(env):
    s = make_scheduler(env)
    result = s.solve([Pod(requests=Resources(cpu=10_000))])
    assert len(result.unschedulable) == 1


def test_gpu_pod_gets_accelerated_type(env):
    s = make_scheduler(env)
    result = s.solve([Pod(requests=Resources({L.RESOURCE_GPU: 1, "cpu": 2}))])
    assert not result.unschedulable
    (node,) = result.new_nodes
    assert all(t.capacity.get(L.RESOURCE_GPU) >= 1 for t in node.feasible_types)


def test_zone_selector_restricts(env):
    s = make_scheduler(env)
    result = s.solve(
        [Pod(requests=Resources(cpu=1), node_selector={L.LABEL_ZONE: "zone-b"})]
    )
    (node,) = result.new_nodes
    assert node.zone_options() == {"zone-b"}


def test_untolerated_taint_unschedulable(env):
    pool = env.default_node_pool(taints=[Taint("team", "ml")])
    s = make_scheduler(env, pools=[pool])
    res_no = s.solve([Pod(requests=Resources(cpu=1))])
    assert len(res_no.unschedulable) == 1
    res_yes = s.solve(
        [Pod(requests=Resources(cpu=1), tolerations=[Toleration("team", "Equal", "ml")])]
    )
    assert not res_yes.unschedulable


def test_pool_weight_priority(env):
    low = env.default_node_pool(name="low", weight=1)
    high = env.default_node_pool(name="high", weight=10)
    s = make_scheduler(env, pools=[low, high])
    result = s.solve([Pod(requests=Resources(cpu=1))])
    assert result.new_nodes[0].pool.name == "high"


def test_zone_topology_spread(env):
    s = make_scheduler(env)
    spread = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("app", "web"),),
    )
    pods = [
        Pod(labels={"app": "web"}, requests=Resources(cpu=3), topology_spread=[spread])
        for _ in range(9)
    ]
    result = s.solve(pods)
    assert not result.unschedulable
    zone_counts = {}
    for n in result.new_nodes:
        zones = n.zone_options()
        assert len(zones) == 1, "spread pods must pin node zones"
        z = next(iter(zones))
        zone_counts[z] = zone_counts.get(z, 0) + len(n.pods)
    assert len(zone_counts) == 3
    assert max(zone_counts.values()) - min(zone_counts.values()) <= 1


def test_spread_domains_respect_pool_zone_restriction(env):
    """Spread domains are zones some pool can actually create nodes in
    (karpenter-core builds them from provisioner requirements): a pool
    restricted to one zone must not wedge a DoNotSchedule spread on the
    zones it can never serve — oracle and tensor paths both."""
    from karpenter_tpu.api import Requirement, Requirements
    from karpenter_tpu.api.requirements import Op
    from karpenter_tpu.scheduling.solver import TensorScheduler

    pool = env.default_node_pool(
        requirements=Requirements(
            [Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])]
        )
    )
    env.default_node_class()
    types = {pool.name: env.instance_types.list(pool=pool)}
    spread = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("app", "web"),),
    )
    pods = [
        Pod(labels={"app": "web"}, requests=Resources(cpu=1),
            topology_spread=[spread])
        for _ in range(6)
    ]
    oracle = Scheduler([pool], types).solve(list(pods))
    assert not oracle.unschedulable
    for ts_path in (TensorScheduler([pool], types),):
        r = ts_path.solve(list(pods))
        assert not r.unschedulable, ts_path.last_path
        for n in r.new_nodes:
            assert n.zone_options() == {"zone-a"}


def test_spread_domains_include_tainted_pools(env):
    """nodeTaintsPolicy defaults to Ignore: a tainted pool's zones still
    COUNT as spread domains (even though the untolerating pod can't land
    there), and the oracle and tensor paths must agree on the outcome."""
    from karpenter_tpu.api import Requirement, Requirements, Taint
    from karpenter_tpu.api.requirements import Op
    from karpenter_tpu.scheduling.solver import TensorScheduler

    nc = env.default_node_class()
    pa = env.default_node_pool(
        name="a",
        requirements=Requirements(
            [Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])]
        ),
    )
    pb = env.default_node_pool(
        name="b", taints=[Taint("team", "ml", "NoSchedule")]
    )
    types = {p.name: env.instance_types.list(p, nc) for p in (pa, pb)}
    spread = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("app", "w"),),
    )
    pods = [
        Pod(labels={"app": "w"}, requests=Resources(cpu=1),
            topology_spread=[spread])
        for _ in range(6)
    ]
    o = Scheduler([pa, pb], types).solve(list(pods))
    t = TensorScheduler([pa, pb], types).solve(list(pods))
    # domains = {a, b, c}; only zone-a has servable capacity for this
    # pod, so DoNotSchedule caps placements at min+maxSkew = 1 — strict
    # but CONSISTENT across both paths
    assert len(o.unschedulable) == len(t.unschedulable) == 5


def test_hostname_anti_affinity_one_per_node(env):
    s = make_scheduler(env)
    anti = PodAffinityTerm(
        topology_key=L.LABEL_HOSTNAME, label_selector=(("app", "solo"),), anti=True
    )
    pods = [
        Pod(labels={"app": "solo"}, requests=Resources(cpu="100m"), pod_affinity=[anti])
        for _ in range(8)
    ]
    result = s.solve(pods)
    assert not result.unschedulable
    assert result.node_count() == 8
    assert all(len(n.pods) == 1 for n in result.new_nodes)


def test_zone_pod_affinity_colocates(env):
    s = make_scheduler(env)
    aff = PodAffinityTerm(topology_key=L.LABEL_ZONE, label_selector=(("app", "db"),))
    pods = [
        Pod(labels={"app": "db"}, requests=Resources(cpu=1), pod_affinity=[aff])
        for _ in range(6)
    ]
    result = s.solve(pods)
    assert not result.unschedulable
    zones = set()
    for n in result.new_nodes:
        zones.update(n.zone_options())
    assert len(zones) == 1  # all anchored to the first pod's zone


def test_existing_node_reused(env):
    sn = StateNode(
        name="node-1",
        provider_id="fake://i-1",
        labels={
            L.LABEL_ZONE: "zone-a",
            L.LABEL_INSTANCE_TYPE: "std1.xlarge",
            L.LABEL_NODEPOOL: "default",
        },
        taints=[],
        allocatable=Resources(cpu=8, memory="30Gi", pods=110),
    )
    s = make_scheduler(env, existing=[sn])
    result = s.solve([Pod(requests=Resources(cpu=1))])
    assert result.node_count() == 0
    assert list(result.existing_placements.values()) == ["node-1"]


def test_daemonset_overhead_charged(env):
    ds = Pod(requests=Resources(cpu=1), is_daemonset=True)
    s = make_scheduler(env, daemonsets=[ds])
    # a pod needing 4 cpu + 1 cpu daemon overhead cannot fit a 4-cpu node
    result = s.solve([Pod(requests=Resources(cpu=4))])
    assert not result.unschedulable
    (node,) = result.new_nodes
    assert all(t.capacity.cpu > 4 for t in node.feasible_types)
    assert node.used.cpu == 5


def test_ffd_prefers_cheapest_type(env):
    s = make_scheduler(env)
    result = s.solve([Pod(requests=Resources(cpu="500m", memory="1Gi"))])
    (node,) = result.new_nodes
    # cheapest feasible offering should be spot on the cheapest family (arm)
    assert node.cheapest_price() < 0.05


def test_member_pods_pin_zone_for_spread_soundness(env):
    """Pods selected by someone else's spread constraint aren't themselves
    restricted (k8s semantics), but their placements MUST be pinned and
    counted so later constrained pods see true skew."""
    s = make_scheduler(env)
    spread = TopologySpreadConstraint(
        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=(("app", "web"),)
    )
    # 1. a constrained pod registers the group and anchors some zone
    r1 = s.solve(
        [Pod(labels={"app": "web"}, requests=Resources(cpu=1), topology_spread=[spread])]
    )
    hot_zone = next(iter(r1.new_nodes[0].zone_options()))
    # 2. unconstrained members land somewhere and MUST be counted
    members = [Pod(labels={"app": "web"}, requests=Resources(cpu=1)) for _ in range(5)]
    r2 = s.solve(members)
    assert not r2.unschedulable
    member_zones = set()
    for n in r2.new_nodes:
        assert len(n.zone_options()) == 1, "member pods must pin node zones"
        member_zones.update(n.zone_options())
    # 3. further constrained pods must avoid the member-heavy zone: its
    #    count (>=5) exceeds floor + maxSkew - 1
    r3 = s.solve(
        [
            Pod(
                labels={"app": "web"},
                requests=Resources(cpu=1),
                topology_spread=[spread],
            )
            for _ in range(2)
        ]
    )
    assert not r3.unschedulable
    landed = set()
    for n in r3.new_nodes:
        landed.update(n.zone_options())
    assert not (landed & member_zones), "member placements were not counted"


def test_tpu_accelerator_selector(env):
    s = make_scheduler(env)
    result = s.solve(
        [
            Pod(
                requests=Resources({L.RESOURCE_TPU: 2, "cpu": 4}),
                node_selector={L.LABEL_INSTANCE_ACCELERATOR_NAME: "tpu-v5e"},
            )
        ]
    )
    assert not result.unschedulable
    (node,) = result.new_nodes
    assert all(t.capacity.get(L.RESOURCE_TPU) >= 2 for t in node.feasible_types)
