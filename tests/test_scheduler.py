"""FFD oracle scheduler semantics (reference designs/bin-packing.md +
website v0.31 concepts/scheduling.md)."""

import pytest

from karpenter_tpu.api import (
    Pod,
    Requirement,
    Resources,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.state.cluster import StateNode
from karpenter_tpu.testing import Environment


@pytest.fixture
def env():
    return Environment()


def make_scheduler(env, pools=None, existing=(), daemonsets=()):
    pools = pools or [env.default_node_pool()]
    env.default_node_class()
    types = {p.name: env.instance_types.list(pool=p) for p in pools}
    return Scheduler(pools, types, existing=existing, daemonsets=daemonsets)


def test_homogeneous_pods_pack_tightly(env):
    s = make_scheduler(env)
    pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(100)]
    result = s.solve(pods)
    assert not result.unschedulable
    # 100 cpu of demand must not open 100 nodes; FFD should pack densely
    assert result.node_count() <= 4
    placed = sum(len(n.pods) for n in result.new_nodes)
    assert placed == 100


def test_pod_too_big_unschedulable(env):
    s = make_scheduler(env)
    result = s.solve([Pod(requests=Resources(cpu=10_000))])
    assert len(result.unschedulable) == 1


def test_gpu_pod_gets_accelerated_type(env):
    s = make_scheduler(env)
    result = s.solve([Pod(requests=Resources({L.RESOURCE_GPU: 1, "cpu": 2}))])
    assert not result.unschedulable
    (node,) = result.new_nodes
    assert all(t.capacity.get(L.RESOURCE_GPU) >= 1 for t in node.feasible_types)


def test_zone_selector_restricts(env):
    s = make_scheduler(env)
    result = s.solve(
        [Pod(requests=Resources(cpu=1), node_selector={L.LABEL_ZONE: "zone-b"})]
    )
    (node,) = result.new_nodes
    assert node.zone_options() == {"zone-b"}


def test_untolerated_taint_unschedulable(env):
    pool = env.default_node_pool(taints=[Taint("team", "ml")])
    s = make_scheduler(env, pools=[pool])
    res_no = s.solve([Pod(requests=Resources(cpu=1))])
    assert len(res_no.unschedulable) == 1
    res_yes = s.solve(
        [Pod(requests=Resources(cpu=1), tolerations=[Toleration("team", "Equal", "ml")])]
    )
    assert not res_yes.unschedulable


def test_pool_weight_priority(env):
    low = env.default_node_pool(name="low", weight=1)
    high = env.default_node_pool(name="high", weight=10)
    s = make_scheduler(env, pools=[low, high])
    result = s.solve([Pod(requests=Resources(cpu=1))])
    assert result.new_nodes[0].pool.name == "high"


def test_zone_topology_spread(env):
    s = make_scheduler(env)
    spread = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("app", "web"),),
    )
    pods = [
        Pod(labels={"app": "web"}, requests=Resources(cpu=3), topology_spread=[spread])
        for _ in range(9)
    ]
    result = s.solve(pods)
    assert not result.unschedulable
    zone_counts = {}
    for n in result.new_nodes:
        zones = n.zone_options()
        assert len(zones) == 1, "spread pods must pin node zones"
        z = next(iter(zones))
        zone_counts[z] = zone_counts.get(z, 0) + len(n.pods)
    assert len(zone_counts) == 3
    assert max(zone_counts.values()) - min(zone_counts.values()) <= 1


def test_spread_domains_respect_pool_zone_restriction(env):
    """Spread domains are zones some pool can actually create nodes in
    (karpenter-core builds them from provisioner requirements): a pool
    restricted to one zone must not wedge a DoNotSchedule spread on the
    zones it can never serve — oracle and tensor paths both."""
    from karpenter_tpu.api import Requirement, Requirements
    from karpenter_tpu.api.requirements import Op
    from karpenter_tpu.scheduling.solver import TensorScheduler

    pool = env.default_node_pool(
        requirements=Requirements(
            [Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])]
        )
    )
    env.default_node_class()
    types = {pool.name: env.instance_types.list(pool=pool)}
    spread = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("app", "web"),),
    )
    pods = [
        Pod(labels={"app": "web"}, requests=Resources(cpu=1),
            topology_spread=[spread])
        for _ in range(6)
    ]
    oracle = Scheduler([pool], types).solve(list(pods))
    assert not oracle.unschedulable
    for ts_path in (TensorScheduler([pool], types),):
        r = ts_path.solve(list(pods))
        assert not r.unschedulable, ts_path.last_path
        for n in r.new_nodes:
            assert n.zone_options() == {"zone-a"}


def test_spread_domains_include_tainted_pools(env):
    """nodeTaintsPolicy defaults to Ignore: a tainted pool's zones still
    COUNT as spread domains (even though the untolerating pod can't land
    there), and the oracle and tensor paths must agree on the outcome."""
    from karpenter_tpu.api import Requirement, Requirements, Taint
    from karpenter_tpu.api.requirements import Op
    from karpenter_tpu.scheduling.solver import TensorScheduler

    nc = env.default_node_class()
    pa = env.default_node_pool(
        name="a",
        requirements=Requirements(
            [Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])]
        ),
    )
    pb = env.default_node_pool(
        name="b", taints=[Taint("team", "ml", "NoSchedule")]
    )
    types = {p.name: env.instance_types.list(p, nc) for p in (pa, pb)}
    spread = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("app", "w"),),
    )
    pods = [
        Pod(labels={"app": "w"}, requests=Resources(cpu=1),
            topology_spread=[spread])
        for _ in range(6)
    ]
    o = Scheduler([pa, pb], types).solve(list(pods))
    t = TensorScheduler([pa, pb], types).solve(list(pods))
    # domains = {a, b, c}; only zone-a has servable capacity for this
    # pod, so DoNotSchedule caps placements at min+maxSkew = 1 — strict
    # but CONSISTENT across both paths
    assert len(o.unschedulable) == len(t.unschedulable) == 5


def test_hostname_anti_affinity_one_per_node(env):
    s = make_scheduler(env)
    anti = PodAffinityTerm(
        topology_key=L.LABEL_HOSTNAME, label_selector=(("app", "solo"),), anti=True
    )
    pods = [
        Pod(labels={"app": "solo"}, requests=Resources(cpu="100m"), pod_affinity=[anti])
        for _ in range(8)
    ]
    result = s.solve(pods)
    assert not result.unschedulable
    assert result.node_count() == 8
    assert all(len(n.pods) == 1 for n in result.new_nodes)


def test_zone_pod_affinity_colocates(env):
    s = make_scheduler(env)
    aff = PodAffinityTerm(topology_key=L.LABEL_ZONE, label_selector=(("app", "db"),))
    pods = [
        Pod(labels={"app": "db"}, requests=Resources(cpu=1), pod_affinity=[aff])
        for _ in range(6)
    ]
    result = s.solve(pods)
    assert not result.unschedulable
    zones = set()
    for n in result.new_nodes:
        zones.update(n.zone_options())
    assert len(zones) == 1  # all anchored to the first pod's zone


def test_existing_node_reused(env):
    sn = StateNode(
        name="node-1",
        provider_id="fake://i-1",
        labels={
            L.LABEL_ZONE: "zone-a",
            L.LABEL_INSTANCE_TYPE: "std1.xlarge",
            L.LABEL_NODEPOOL: "default",
        },
        taints=[],
        allocatable=Resources(cpu=8, memory="30Gi", pods=110),
    )
    s = make_scheduler(env, existing=[sn])
    result = s.solve([Pod(requests=Resources(cpu=1))])
    assert result.node_count() == 0
    assert list(result.existing_placements.values()) == ["node-1"]


def test_daemonset_overhead_charged(env):
    ds = Pod(requests=Resources(cpu=1), is_daemonset=True)
    s = make_scheduler(env, daemonsets=[ds])
    # a pod needing 4 cpu + 1 cpu daemon overhead cannot fit a 4-cpu node
    result = s.solve([Pod(requests=Resources(cpu=4))])
    assert not result.unschedulable
    (node,) = result.new_nodes
    assert all(t.capacity.cpu > 4 for t in node.feasible_types)
    assert node.used.cpu == 5


def test_ffd_prefers_cheapest_type(env):
    s = make_scheduler(env)
    result = s.solve([Pod(requests=Resources(cpu="500m", memory="1Gi"))])
    (node,) = result.new_nodes
    # cheapest feasible offering should be spot on the cheapest family (arm)
    assert node.cheapest_price() < 0.05


def test_member_pods_pin_zone_for_spread_soundness(env):
    """Pods selected by someone else's spread constraint aren't themselves
    restricted (k8s semantics), but their placements MUST be pinned and
    counted so later constrained pods see true skew."""
    s = make_scheduler(env)
    spread = TopologySpreadConstraint(
        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=(("app", "web"),)
    )
    # 1. a constrained pod registers the group and anchors some zone
    r1 = s.solve(
        [Pod(labels={"app": "web"}, requests=Resources(cpu=1), topology_spread=[spread])]
    )
    hot_zone = next(iter(r1.new_nodes[0].zone_options()))
    # 2. unconstrained members land somewhere and MUST be counted
    members = [Pod(labels={"app": "web"}, requests=Resources(cpu=1)) for _ in range(5)]
    r2 = s.solve(members)
    assert not r2.unschedulable
    member_zones = set()
    for n in r2.new_nodes:
        assert len(n.zone_options()) == 1, "member pods must pin node zones"
        member_zones.update(n.zone_options())
    # 3. further constrained pods must avoid the member-heavy zone: its
    #    count (>=5) exceeds floor + maxSkew - 1
    r3 = s.solve(
        [
            Pod(
                labels={"app": "web"},
                requests=Resources(cpu=1),
                topology_spread=[spread],
            )
            for _ in range(2)
        ]
    )
    assert not r3.unschedulable
    landed = set()
    for n in r3.new_nodes:
        landed.update(n.zone_options())
    assert not (landed & member_zones), "member placements were not counted"


def test_tpu_accelerator_selector(env):
    s = make_scheduler(env)
    result = s.solve(
        [
            Pod(
                requests=Resources({L.RESOURCE_TPU: 2, "cpu": 4}),
                node_selector={L.LABEL_INSTANCE_ACCELERATOR_NAME: "tpu-v5e"},
            )
        ]
    )
    assert not result.unschedulable
    (node,) = result.new_nodes
    assert all(t.capacity.get(L.RESOURCE_TPU) >= 2 for t in node.feasible_types)


def test_gang_anchor_avoids_node_too_small_for_group(env):
    """A co-location anchor reserves its whole group's total: a live node
    with room for only part of the group is skipped, so the followers are
    never stranded when a node that holds everyone exists."""
    sn = StateNode(
        name="tight-1",
        provider_id="fake://i-9",
        labels={
            L.LABEL_ZONE: "zone-a",
            L.LABEL_INSTANCE_TYPE: "std1.xlarge",
            L.LABEL_NODEPOOL: "default",
        },
        taints=[],
        allocatable=Resources(cpu=8, memory="30Gi", pods=110),
        used=Resources(cpu=6),
    )
    term = PodAffinityTerm(
        topology_key=L.LABEL_HOSTNAME, label_selector=(("pair", "g"),)
    )
    group = [
        Pod(labels={"pair": "g"}, requests=Resources(cpu=1), pod_affinity=[term])
        for _ in range(3)
    ]
    s = make_scheduler(env, existing=[sn])
    result = s.solve(group)
    assert not result.unschedulable
    # 2 free cpu on the live node can't hold the 3-cpu gang: all three
    # must land together on one fresh node
    assert not result.existing_placements
    assert result.node_count() == 1
    assert len(result.new_nodes[0].pods) == 3


def test_gang_too_big_for_any_node_partial_places(env):
    """When NO node admits the gang total the anchor falls back to
    per-pod placement (kube-scheduler's greedy partial semantics)."""
    s = make_scheduler(env)
    biggest = max(
        t.capacity.cpu
        for ts in s.instance_types.values()
        for t in ts
    )
    n = int(biggest // 4) + 2
    term = PodAffinityTerm(
        topology_key=L.LABEL_HOSTNAME, label_selector=(("pair", "big"),)
    )
    group = [
        Pod(labels={"pair": "big"}, requests=Resources(cpu=4), pod_affinity=[term])
        for _ in range(n)
    ]
    result = s.solve(group)
    placed = sum(len(vn.pods) for vn in result.new_nodes)
    assert placed > 0  # partial, not all-or-nothing
    assert len(result.unschedulable) == n - placed
    assert result.node_count() == 1  # everyone placed shares the anchor node


def test_gang_with_live_anchor_joins_existing(env):
    """A gang whose selector matches a pod bound on a live node JOINS that
    node (no fresh-node reserve): live-member co-location semantics."""
    bound = Pod(labels={"pair": "g"}, requests=Resources(cpu=1))
    sn = StateNode(
        name="holder-1",
        provider_id="fake://i-8",
        labels={
            L.LABEL_ZONE: "zone-a",
            L.LABEL_INSTANCE_TYPE: "std1.xlarge",
            L.LABEL_NODEPOOL: "default",
        },
        taints=[],
        allocatable=Resources(cpu=8, memory="30Gi", pods=110),
        pods=[bound],
        used=Resources(cpu=1),
    )
    term = PodAffinityTerm(
        topology_key=L.LABEL_HOSTNAME, label_selector=(("pair", "g"),)
    )
    group = [
        Pod(labels={"pair": "g"}, requests=Resources(cpu=1), pod_affinity=[term])
        for _ in range(2)
    ]
    s = make_scheduler(env, existing=[sn])
    result = s.solve(group)
    assert not result.unschedulable
    assert set(result.existing_placements.values()) == {"holder-1"}


def test_unknown_selector_operator_matches_nothing(env):
    """An invalid matchExpressions operator must not throw inside the
    scheduling loop — kube's contract for an invalid selector is to match
    nothing."""
    from karpenter_tpu.api.objects import selector_matches

    assert not selector_matches({"a": "b"}, (), (("a", "Bogus", ("b",)),))
    # a spread carrying the bogus expression schedules without raising
    c = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("svc", "x"),),
        match_expressions=(("tier", "Bogus", ("gold",)),),
    )
    s = make_scheduler(env)
    result = s.solve(
        [Pod(labels={"svc": "x"}, requests=Resources(cpu=1), topology_spread=[c])]
    )
    assert not result.unschedulable


def _zoned_type(name, cpu, zone, price):
    """Hand-built type mirroring the provider's shape: type requirements
    carry the zone/capacity-type keys its offerings span
    (providers/instancetype.py:265), so required-zone pods are admitted."""
    from karpenter_tpu.api import InstanceType, Requirement, Requirements
    from karpenter_tpu.api.objects import Offering, Offerings
    from karpenter_tpu.api.requirements import Op as _Op

    return InstanceType(
        name=name,
        requirements=Requirements(
            [
                Requirement(L.LABEL_INSTANCE_TYPE, _Op.IN, [name]),
                Requirement(L.LABEL_ZONE, _Op.IN, [zone]),
                Requirement(L.LABEL_CAPACITY_TYPE, _Op.IN, ["on-demand"]),
            ]
        ),
        capacity=Resources(cpu=cpu, memory=f"{cpu*4}Gi", pods=110),
        offerings=Offerings(
            [Offering(zone=zone, capacity_type="on-demand", price=price)]
        ),
    )


def test_gang_relaxed_attempt_keeps_reserve(env):
    """Preference-carrying gangs stay gang-aware through relaxation: if
    strict+reserve fails, relaxed+reserve runs BEFORE the plain partial
    fallback — the whole group lands on the big node in the non-preferred
    zone instead of stranding a follower in the preferred one."""
    from karpenter_tpu.api import NodePool

    small = _zoned_type("small-8", 8, "zone-a", 1.0)
    big = _zoned_type("big-16", 16, "zone-b", 2.0)
    pool = NodePool(name="p")
    s = Scheduler([pool], {"p": [small, big]})
    term = PodAffinityTerm(
        topology_key=L.LABEL_HOSTNAME, label_selector=(("pair", "g"),)
    )
    group = [
        Pod(
            labels={"pair": "g"},
            requests=Resources(cpu=4),
            pod_affinity=[term],
            preferred_affinity=[Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])],
        )
        for _ in range(3)
    ]
    result = s.solve(group)
    assert not result.unschedulable, result.unschedulable
    assert result.node_count() == 1
    vn = result.new_nodes[0]
    assert len(vn.pods) == 3
    # the 12-cpu gang only fits the zone-b type
    assert {t.name for t in vn.feasible_types} == {"big-16"}


def test_interleaved_gangs_do_not_double_book_reserve(env):
    """Two gangs whose members interleave in input order must not anchor
    on the same node and strand each other's followers: the anchor's
    contiguous gang pass consumes the reservation before the next gang's
    anchor probes."""
    from karpenter_tpu.api import NodePool

    only16 = _zoned_type("only-16", 16, "zone-a", 1.0)
    pool = NodePool(name="p")
    s = Scheduler([pool], {"p": [only16]})
    pods = []
    for i in range(3):  # interleave: A,B,A,B,A,B
        for g in ("ga", "gb"):
            pods.append(
                Pod(
                    labels={"pair": g},
                    requests=Resources(cpu=4),
                    pod_affinity=[
                        PodAffinityTerm(
                            topology_key=L.LABEL_HOSTNAME,
                            label_selector=(("pair", g),),
                        )
                    ],
                )
            )
    result = s.solve(pods)
    # each 12-cpu gang fits a 16-cpu node alone but not together: two
    # nodes, zero stranded
    assert not result.unschedulable, result.unschedulable
    assert result.node_count() == 2
    by_gang = {}
    for vn in result.new_nodes:
        for p in vn.pods:
            by_gang.setdefault(p.labels["pair"], set()).add(vn.name)
    assert all(len(v) == 1 for v in by_gang.values()), by_gang


def test_gang_reserve_prefers_later_or_term_over_partial(env):
    """All reserved OR-term attempts run before any plain fallback: a
    gang whose first term lands in a zone too small for the group takes
    the second term's zone whole instead of stranding a follower."""
    from karpenter_tpu.api import NodePool

    small_a = _zoned_type("small-8a", 8, "zone-a", 1.0)
    big_b = _zoned_type("big-16b", 16, "zone-b", 2.0)
    pool = NodePool(name="p")
    s = Scheduler([pool], {"p": [small_a, big_b]})
    term = PodAffinityTerm(
        topology_key=L.LABEL_HOSTNAME, label_selector=(("pair", "g"),)
    )
    group = [
        Pod(
            labels={"pair": "g"},
            requests=Resources(cpu=4),
            pod_affinity=[term],
            affinity_terms=[
                (Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"]),),
                (Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"]),),
            ],
        )
        for _ in range(3)
    ]
    result = s.solve(group)
    assert not result.unschedulable, result.unschedulable
    assert result.node_count() == 1
    assert {t.name for t in result.new_nodes[0].feasible_types} == {"big-16b"}


def test_spread_pod_retries_next_allowed_zone(env):
    """A DoNotSchedule zone spread must not wedge on the balance-optimal
    zone when only another allowed zone has a fitting type: the zone walk
    falls through to the next-balanced allowed domain."""
    from karpenter_tpu.api import NodePool

    tiny_a = _zoned_type("tiny-2a", 2, "zone-a", 0.5)
    big_b = _zoned_type("big-16b2", 16, "zone-b", 2.0)
    pool = NodePool(name="p")
    s = Scheduler([pool], {"p": [tiny_a, big_b]})
    c = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("app", "w"),),
    )
    pod = Pod(labels={"app": "w"}, requests=Resources(cpu=4), topology_spread=[c])
    result = s.solve([pod])
    assert not result.unschedulable, result.unschedulable
    assert result.new_nodes[0].zone_options() == {"zone-b"}


def test_preference_peeling_keeps_satisfiable_preference(env):
    """Term-by-term relaxation (karpenter-core RelaxMinimal): a pod whose
    lower-priority preference is unsatisfiable keeps the satisfiable
    higher-priority one instead of losing both."""
    s = make_scheduler(env)
    pod = Pod(
        requests=Resources(cpu=1),
        preferred_affinity=[
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"]),       # satisfiable
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-nowhere"]),  # not
        ],
    )
    result = s.solve([pod])
    assert not result.unschedulable
    # the peel dropped only the impossible preference: zone-b is honored
    assert result.new_nodes[0].zone_options() == {"zone-b"}


def test_preference_peeling_drops_all_when_none_satisfiable(env):
    s = make_scheduler(env)
    pod = Pod(
        requests=Resources(cpu=1),
        preferred_affinity=[
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-nope"]),
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-nah"]),
        ],
    )
    result = s.solve([pod])
    assert not result.unschedulable
    assert len(result.new_nodes[0].zone_options()) >= 2  # unpinned


def test_peeling_keeps_soft_spread_while_dropping_preference(env):
    """ScheduleAnyway spreads relax LAST: a pod dropping an impossible
    preference still honors its soft spread on that attempt."""
    s = make_scheduler(env)
    c = TopologySpreadConstraint(
        max_skew=1,
        topology_key=L.LABEL_ZONE,
        label_selector=(("app", "w"),),
        when_unsatisfiable="ScheduleAnyway",
    )
    pods = [
        Pod(
            labels={"app": "w"},
            requests=Resources(cpu=1),
            topology_spread=[c],
            preferred_affinity=[Requirement(L.LABEL_ZONE, Op.IN, ["zone-nope"])],
        )
        for _ in range(6)
    ]
    result = s.solve(pods)
    assert not result.unschedulable
    counts = {}
    for vn in result.new_nodes:
        zones = vn.zone_options()
        assert len(zones) == 1
        counts[next(iter(zones))] = counts.get(next(iter(zones)), 0) + len(vn.pods)
    assert max(counts.values()) - min(counts.values()) <= 1, counts


def test_preference_order_distinct_pods_do_not_share_cache(env):
    """List order is priority under peeling, so pods with the same
    preferences in different order must not share a class or a try_add
    cache entry: each keeps ITS OWN satisfiable higher-priority pick."""
    s = make_scheduler(env)
    bad = Requirement(L.LABEL_ZONE, Op.IN, ["zone-nowhere"])
    good = Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"])
    p0 = Pod(requests=Resources(cpu=1), preferred_affinity=[bad, good])
    p1 = Pod(requests=Resources(cpu=1), preferred_affinity=[good, bad])
    p2 = Pod(requests=Resources(cpu=1), preferred_affinity=[bad, good])
    result = s.solve([p0, p2, p1])
    assert not result.unschedulable
    # p1 keeps zone-b (its higher-priority pref); p0/p2 peel down to no
    # preferences and pack beside it — one node suffices
    assert result.node_count() == 1
    assert result.new_nodes[0].zone_options() == {"zone-b"}


def test_custom_topology_key_spread(env):
    """Spreads on arbitrary node-label keys (scheduling.md:319-331):
    domains come from the pool templates' values for the key, and the
    oracle balances across them like zones."""
    ra = env.default_node_pool(name="rack-a", labels={"example.com/rack": "r1"})
    rb = env.default_node_pool(name="rack-b", labels={"example.com/rack": "r2"})
    s = make_scheduler(env, pools=[ra, rb])
    c = TopologySpreadConstraint(
        max_skew=1,
        topology_key="example.com/rack",
        label_selector=(("app", "w"),),
    )
    pods = [
        Pod(labels={"app": "w"}, requests=Resources(cpu=1), topology_spread=[c])
        for _ in range(6)
    ]
    result = s.solve(pods)
    assert not result.unschedulable, result.unschedulable
    counts = {}
    for vn in result.new_nodes:
        rack = vn.requirements.get("example.com/rack").any_value()
        counts[rack] = counts.get(rack, 0) + len(vn.pods)
    assert set(counts) == {"r1", "r2"}
    assert max(counts.values()) - min(counts.values()) <= 1, counts


def test_custom_key_spread_respects_live_counts(env):
    """Live pods matched by the selector count toward their node's rack
    domain, skewing new placements toward the emptier rack."""
    ra = env.default_node_pool(name="rack-a", labels={"example.com/rack": "r1"})
    rb = env.default_node_pool(name="rack-b", labels={"example.com/rack": "r2"})
    bound = [
        Pod(labels={"app": "w"}, requests=Resources(cpu=1)) for _ in range(2)
    ]
    sn = StateNode(
        name="live-1",
        provider_id="fake://live-1",
        labels={
            L.LABEL_ZONE: "zone-a",
            "example.com/rack": "r1",
            L.LABEL_NODEPOOL: "rack-a",
        },
        taints=[],
        allocatable=Resources(cpu=2, memory="8Gi", pods=110),
        pods=bound,
        used=Resources(cpu=2),
    )
    s = make_scheduler(env, pools=[ra, rb], existing=[sn])
    c = TopologySpreadConstraint(
        max_skew=1,
        topology_key="example.com/rack",
        label_selector=(("app", "w"),),
    )
    pods = [
        Pod(labels={"app": "w"}, requests=Resources(cpu=1), topology_spread=[c])
        for _ in range(2)
    ]
    result = s.solve(pods)
    assert not result.unschedulable, result.unschedulable
    # r1 already holds 2; both new pods must land in r2 to stay in skew
    for vn in result.new_nodes:
        assert vn.requirements.get("example.com/rack").any_value() == "r2"


def test_custom_key_spread_honors_node_selector_universe(env):
    """nodeAffinityPolicy=Honor applies to custom keys too: a pod pinned
    to one rack by its own node selector measures skew over {r1} only —
    r2's zero count must not wedge it."""
    ra = env.default_node_pool(name="rack-a", labels={"example.com/rack": "r1"})
    rb = env.default_node_pool(name="rack-b", labels={"example.com/rack": "r2"})
    s = make_scheduler(env, pools=[ra, rb])
    c = TopologySpreadConstraint(
        max_skew=1,
        topology_key="example.com/rack",
        label_selector=(("app", "w"),),
    )
    pods = [
        Pod(
            labels={"app": "w"},
            requests=Resources(cpu=1),
            node_selector={"example.com/rack": "r1"},
            topology_spread=[c],
        )
        for _ in range(3)
    ]
    result = s.solve(pods)
    assert not result.unschedulable, result.unschedulable
    for vn in result.new_nodes:
        assert vn.requirements.get("example.com/rack").any_value() == "r1"
