"""Chaos-hardening suite (ISSUE 2): sustained seeded fault schedules on the
fake cloud, degraded-but-working provisioning, crash-contained reconcile,
and the seeded chaos soak that runs the full Operator through an API storm
and asserts the survival invariants — no duplicate launches, no leaked
instances, every pod scheduled once the faults clear, no controller
permanently wedged."""

import pytest

from karpenter_tpu.api import Pod, Resources, Settings
from karpenter_tpu.cloud.fake.backend import (
    ChaosEngine,
    CloudAPIError,
    FakeCloud,
    MachineShape,
)
from karpenter_tpu.cloud.retry import OPEN
from karpenter_tpu.operator import Operator
from karpenter_tpu.sim.report import build_report
from karpenter_tpu.sim.runner import ScenarioRunner, chaos_soak_scenario
from karpenter_tpu.state.kube import KubeStore
from karpenter_tpu.testing import Environment
from karpenter_tpu.utils.clock import FakeClock

SHAPES = [
    MachineShape(name=f"std1.{s}", cpu=float(c), memory=c * 4 * 2**30,
                 od_price=0.05 * c)
    for c, s in ((2, "medium"), (4, "large"), (8, "xlarge"), (16, "2xlarge"))
]

# fast backoffs so fault-heavy suites spend fake seconds, not real ones
FAST = dict(
    cloud_max_retries=2,
    cloud_retry_budget_per_tick=20,
    cloud_backoff_base=0.005,
    cloud_backoff_max=0.02,
    cloud_circuit_failure_threshold=4,
    cloud_circuit_reset_timeout=5.0,
    controller_backoff_base=0.5,
    controller_backoff_max=4.0,
)


def _cloud():
    clock = FakeClock()
    return clock, FakeCloud(clock, shapes=SHAPES).with_default_topology()


class TestChaosEngine:
    def test_blackout_window(self):
        clock, cloud = _cloud()
        t = clock.now()
        cloud.chaos.add_blackout(t + 10, 20.0, apis=["DescribeSubnets"])
        assert cloud.describe_subnets([]) == []  # before the window
        clock.step(15.0)
        with pytest.raises(CloudAPIError) as exc:
            cloud.describe_subnets([])
        assert exc.value.code == "ServiceUnavailable"
        cloud.describe_instances()  # other APIs unaffected
        clock.step(20.0)
        assert cloud.describe_subnets([]) == []  # window passed

    def test_full_api_blackout(self):
        clock, cloud = _cloud()
        cloud.chaos.add_blackout(clock.now(), 10.0)  # apis=None -> everything
        for call in (
            lambda: cloud.describe_instance_types(),
            lambda: cloud.describe_instances(),
            lambda: cloud.get_products(),
        ):
            with pytest.raises(CloudAPIError):
                call()

    def test_throttle_burst(self):
        clock, cloud = _cloud()
        cloud.chaos.add_throttle_burst(clock.now(), 5.0)
        with pytest.raises(CloudAPIError) as exc:
            cloud.describe_instances()
        assert exc.value.code == "RequestLimitExceeded"

    def test_error_rate_is_seeded_and_reproducible(self):
        outcomes = []
        for _ in range(2):
            clock, cloud = _cloud()
            cloud.chaos.reseed(42)
            cloud.chaos.set_error_rate("DescribeInstances", 0.5)
            run = []
            for _ in range(20):
                try:
                    cloud.describe_instances()
                    run.append(True)
                except CloudAPIError:
                    run.append(False)
            outcomes.append(run)
        assert outcomes[0] == outcomes[1]
        assert True in outcomes[0] and False in outcomes[0]

    def test_latency_rides_the_injected_clock(self):
        clock, cloud = _cloud()
        cloud.chaos.set_latency("DescribeInstances", 1.5)
        t0 = clock.now()
        cloud.describe_instances()
        assert clock.now() == t0 + 1.5

    def test_partial_fleet(self):
        clock, cloud = _cloud()
        overrides = [{"instance_type": "std1.large", "zone": "zone-a",
                      "subnet_id": "subnet-0"}]
        cloud.chaos.set_partial_fleet(1.0)  # withhold everything
        insts, errs = cloud.create_fleet(overrides=overrides,
                                         capacity_type="on-demand", count=3)
        assert not insts
        assert errs and errs[0].code == "InsufficientInstanceCapacity"
        cloud.chaos.set_partial_fleet(0.0)
        insts, errs = cloud.create_fleet(overrides=overrides,
                                         capacity_type="on-demand", count=3)
        assert len(insts) == 3 and not errs

    def test_clear_stops_everything(self):
        clock, cloud = _cloud()
        cloud.chaos.set_error_rate("*", 1.0)
        cloud.chaos.add_blackout(clock.now(), 1e9)
        cloud.chaos.set_partial_fleet(1.0)
        with pytest.raises(CloudAPIError):
            cloud.describe_instances()
        cloud.chaos.clear()
        cloud.describe_instances()
        assert cloud.chaos.fleet_shortfall(10) == 0

    def test_engine_is_per_cloud_and_detachable(self):
        clock, cloud = _cloud()
        assert isinstance(cloud.chaos, ChaosEngine)
        cloud.chaos.enabled = False
        cloud.chaos.set_error_rate("*", 1.0)
        cloud.describe_instances()  # disabled engine injects nothing


class TestPreflightRetry:
    def test_one_shot_transient_error_no_longer_aborts_construction(self):
        clock, cloud = _cloud()
        cloud.recorder.set_next_error(
            "DescribeInstanceTypes", CloudAPIError("InternalError")
        )
        op = Operator(
            cloud, KubeStore(),
            settings=Settings(cluster_name="t", **FAST),
        )
        assert op.instance_types is not None
        assert cloud.recorder.count("DescribeInstanceTypes") >= 2

    def test_throttle_burst_at_boot_retried(self):
        clock, cloud = _cloud()
        cloud.recorder.set_error_sequence(
            "DescribeInstanceTypes",
            [CloudAPIError("RequestLimitExceeded")] * 2,
        )
        Operator(cloud, KubeStore(), settings=Settings(cluster_name="t", **FAST))

    def test_persistent_failure_still_fails_fast_with_context(self):
        clock, cloud = _cloud()
        cloud.recorder.set_next_error(
            "DescribeInstanceTypes", CloudAPIError("UnauthorizedOperation")
        )
        with pytest.raises(RuntimeError, match="preflight"):
            Operator(cloud, KubeStore(),
                     settings=Settings(cluster_name="t", **FAST))


class TestCrashContainment:
    def _env(self):
        env = Environment(
            shapes=SHAPES, settings=Settings(cluster_name="test", **FAST)
        )
        env.default_node_class()
        env.default_node_pool()
        return env

    def test_raising_controller_is_contained_and_requeued(self):
        env = self._env()
        op = env.operator
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise RuntimeError("boom")

        orig = op.tagging.reconcile
        op.tagging.reconcile = boom
        env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="1Gi")))
        env.step(1.0)  # must not raise out of reconcile_once
        labels = {"controller": "tagging"}
        assert env.registry.counter(
            "karpenter_controller_reconcile_errors_total", labels
        ) == 1
        assert env.registry.gauge(
            "karpenter_tpu_controller_healthy", labels
        ) == 0.0
        # the rest of the sequence proceeded: provisioner stayed healthy
        assert env.registry.gauge(
            "karpenter_tpu_controller_healthy", {"controller": "provisioner"}
        ) == 1.0
        # inside the backoff window the controller is skipped
        n = calls["n"]
        env.step(0.1)
        assert calls["n"] == n
        # due again after the backoff: it runs (and fails, doubling the delay)
        env.step(1.0)
        assert calls["n"] == n + 1
        first_delay = FAST["controller_backoff_base"]
        assert op._ctrl_backoff["tagging"][1] == pytest.approx(
            min(first_delay * 2, FAST["controller_backoff_max"])
        )
        # recovery: a clean reconcile clears the backoff and health flips
        op.tagging.reconcile = orig
        env.step(10.0)
        assert "tagging" not in op._ctrl_backoff
        assert env.registry.gauge(
            "karpenter_tpu_controller_healthy", labels
        ) == 1.0
        # and the pod put down during the crash window still got scheduled
        env.settle()
        assert not env.kube.pending_pods()

    def test_backoff_is_capped(self):
        env = self._env()
        op = env.operator

        def boom():
            raise RuntimeError("boom")

        op.tagging.reconcile = boom
        for _ in range(10):
            env.step(FAST["controller_backoff_max"] + 1)
        assert op._ctrl_backoff["tagging"][1] == FAST["controller_backoff_max"]


class TestDegradedProvisioning:
    def test_subnet_outage_serves_last_good_and_reports_staleness(self):
        env = Environment(
            shapes=SHAPES,
            settings=Settings(
                cluster_name="test", **{**FAST,
                                        "cloud_circuit_reset_timeout": 1e6}
            ),
        )
        env.default_node_class()
        env.default_node_pool()
        env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="1Gi")))
        env.settle()
        assert not env.kube.pending_pods()
        # total, sustained DescribeSubnets outage from here on
        env.cloud.chaos.add_blackout(env.clock.now(), 1e9,
                                     apis=["DescribeSubnets"])
        env.subnets.invalidate()  # force the next list through the dead API
        env.kube.put_pod(Pod(requests=Resources(cpu=2, memory="1Gi")))
        env.settle()
        # provisioning succeeded from the last-good subnet view
        assert not env.kube.pending_pods()
        assert env.registry.gauge(
            "karpenter_provider_cache_stale_seconds", {"provider": "subnet"}
        ) > 0
        # the breaker opened, so the dead API is no longer hammered
        assert env.operator.retrying.circuit_state("describe_subnets") == OPEN

    def test_pricing_refresh_outage_cannot_kill_the_tick(self):
        env = Environment(
            shapes=SHAPES, settings=Settings(cluster_name="test", **FAST)
        )
        env.default_node_class()
        env.default_node_pool()
        env.cloud.chaos.add_blackout(
            env.clock.now(), 1e12,
            apis=["GetProducts", "DescribeSpotPriceHistory"],
        )
        env.clock.step(12 * 3600 + 1)  # due for the 12h pricing refresh
        env.operator.reconcile_once()  # must not raise
        assert env.registry.gauge(
            "karpenter_provider_cache_stale_seconds", {"provider": "pricing"}
        ) > 0
        # scheduling still works on the seeded price table
        env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="1Gi")))
        env.settle()
        assert not env.kube.pending_pods()
        # a FAILED refresh is re-attempted on the short retry cadence once
        # the API heals — not after another 12h of stale prices
        from karpenter_tpu.providers.pricing import PRICING_RETRY_PERIOD

        env.cloud.chaos.clear()
        env.clock.step(PRICING_RETRY_PERIOD + 1)
        env.operator.reconcile_once()
        assert env.registry.gauge(
            "karpenter_provider_cache_stale_seconds", {"provider": "pricing"}
        ) == 0.0


# --------------------------------------------------------------------- soak
#
# The seeded soak now runs on the simulator (karpenter_tpu/sim/): the
# scenario carries the same mixed fault schedule + workload churn the
# hand-rolled loop here used to build, the runner owns the environment
# stepping, and sim/invariants.py asserts a STRICT SUPERSET of the old
# final checks — no duplicate launches / no leaked instances / all pods
# scheduled after fault clearance / no wedged controller, plus per-tick
# no-double-launch, registered==launched, disruption-budget and
# schedule-deadline checks the old loop never made.


def _soak(seed: int, faulty_ticks: int, total_ticks: int) -> ScenarioRunner:
    """Run the full Operator under a seeded mixed fault schedule (error
    rates, throttle bursts, full and partial blackouts, injected latency,
    partial CreateFleet fulfillment) with workload churn, then clear the
    faults and give the system the recovery windows its caches need (ICE
    TTL 180s, GC grace 30s); raise on any invariant violation."""
    scenario = chaos_soak_scenario(faulty_ticks)
    scenario.shapes = SHAPES
    runner = ScenarioRunner(scenario, seed=seed, ticks=total_ticks)
    runner.run()  # a raising reconcile_once fails the soak
    runner.checker.raise_on_violations()
    return runner


@pytest.mark.chaos
@pytest.mark.sim
def test_chaos_soak_short():
    """Tier-1 seeded soak: ~80 ticks, faults clear at tick 60."""
    runner = _soak(seed=7, faulty_ticks=60, total_ticks=80)
    # the scenario actually exercised the cluster (guards against the
    # scenario silently degenerating into a no-op run)
    report = build_report(runner)
    assert report["pods"]["created"] > 10
    assert report["nodes"]["launched"] > 0
    assert report["invariants"]["checked_ticks"] >= 80


@pytest.mark.chaos
@pytest.mark.sim
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chaos_soak_long(seed):
    """The multi-hundred-tick soak (slow): 300 ticks, faults clear at 240."""
    _soak(seed=seed, faulty_ticks=240, total_ticks=300)
