"""Pallas packer parity: the fused kernel must match the scan kernel
placement-for-placement (conftest runs it through the interpreter on the
virtual CPU mesh; bench runs it compiled on the real chip)."""

import numpy as np
import pytest

from karpenter_tpu.api import Pod, Requirement, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.ops import pallas_packer
from karpenter_tpu.ops.packer import run_pack
from karpenter_tpu.ops.tensorize import compile_problem
from karpenter_tpu.testing import Environment


@pytest.fixture(scope="module")
def setup():
    env = Environment()
    pool = env.default_node_pool()
    nc = env.default_node_class()
    types = env.instance_types.list(pool, nc)
    return env, pool, types


def assert_parity(prob, objective="nodes"):
    scan = run_pack(prob, objective=objective)
    fused = pallas_packer.run_pack_pallas(prob, objective=objective)
    scan_take = np.asarray(scan.take)
    G = len(prob.classes)
    # per-class totals and per-slot aggregate placements must agree
    np.testing.assert_array_equal(
        scan_take[:G].sum(axis=1), fused.take[:G].sum(axis=1)
    )
    np.testing.assert_array_equal(
        np.asarray(scan.leftover)[:G], fused.leftover[:G]
    )
    ks = min(scan_take.shape[1], fused.take.shape[1])
    np.testing.assert_array_equal(
        scan_take[:G, :ks], fused.take[:G, :ks]
    )
    np.testing.assert_array_equal(
        np.asarray(scan.node_cfg)[:ks][np.asarray(scan.node_pods)[:ks] > 0],
        fused.node_cfg[:ks][fused.node_pods[:ks] > 0],
    )
    return fused


class TestPallasParity:
    def test_homogeneous(self, setup):
        env, pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(300)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert pallas_packer.supports(prob)
        assert_parity(prob)

    def test_heterogeneous_classes(self, setup):
        env, pool, types = setup
        sizes = [
            Resources(cpu=0.25, memory="512Mi"),
            Resources(cpu=1, memory="2Gi"),
            Resources(cpu=2, memory="4Gi"),
            Resources(cpu=4, memory="16Gi"),
        ]
        pods = [Pod(requests=sizes[i % 4]) for i in range(400)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert_parity(prob)

    def test_cost_objective(self, setup):
        env, pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(200)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert_parity(prob, objective="cost")

    def test_anti_affinity_cap(self, setup):
        env, pool, types = setup
        sel = (("app", "d"),)
        pods = [
            Pod(
                labels={"app": "d"},
                requests=Resources(cpu=0.25),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME, label_selector=sel,
                        anti=True,
                    )
                ],
            )
            for _ in range(50)
        ]
        prob = compile_problem(pods, [pool], {pool.name: types})
        fused = assert_parity(prob)
        assert (fused.take[: len(prob.classes)].max(axis=0) <= 1).all()

    def test_zone_spread_split(self, setup):
        env, pool, types = setup
        sel = (("app", "z"),)
        pods = [
            Pod(
                labels={"app": "z"},
                requests=Resources(cpu=1, memory="1Gi"),
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(60)
        ]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert_parity(prob)

    def test_mixed_constraints(self, setup):
        env, pool, types = setup
        pods = []
        for i in range(150):
            p = Pod(requests=Resources(cpu=[0.5, 1, 2][i % 3], memory="1Gi"))
            if i % 4 == 0:
                p.node_selector = {L.LABEL_ARCH: "arm64"}
            if i % 5 == 0:
                p.required_affinity = [
                    Requirement(L.LABEL_ZONE, Op.IN, ["zone-a", "zone-b"])
                ]
            pods.append(p)
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert_parity(prob)

    def test_existing_nodes_prefill(self, setup):
        env, pool, types = setup
        from karpenter_tpu.state.cluster import StateNode

        existing = [
            StateNode(
                name=f"node-{i}",
                provider_id=f"i-{i}",
                labels={
                    L.LABEL_ARCH: "amd64",
                    L.LABEL_OS: "linux",
                    L.LABEL_ZONE: "zone-a",
                },
                taints=[],
                allocatable=Resources(cpu=8, memory="32Gi", pods=110),
            )
            for i in range(3)
        ]
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(20)]
        prob = compile_problem(
            pods, [pool], {pool.name: types}, existing=existing
        )
        fused = assert_parity(prob)
        # existing slots filled first
        assert fused.take[:, :3].sum() > 0

    def test_pool_priority_splits_signature_rows(self, setup):
        """Classes sharing a constraint signature can carry DIFFERENT
        feasibility rows (the pool-weight priority pass restricts per
        class by request size) — the fused kernel must not collapse them
        onto one admission row.  Regression: small pods restricted to a
        high-weight small-instance pool once masked the big pods' only
        feasible (low-weight) pool, leaving them unschedulable."""
        from karpenter_tpu.api import Requirements
        from karpenter_tpu.testing import Environment

        env = Environment()
        nc = env.default_node_class()
        small = env.default_node_pool(
            name="small",
            weight=100,
            requirements=Requirements(
                [Requirement(L.LABEL_INSTANCE_CPU, Op.LT, ["9"])]
            ),
        )
        big = env.default_node_pool(
            name="big",
            weight=0,
            requirements=Requirements(
                [Requirement(L.LABEL_INSTANCE_CPU, Op.GT, ["31"])]
            ),
        )
        inventory = {
            "small": env.instance_types.list(small, nc),
            "big": env.instance_types.list(big, nc),
        }
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(30)]
        pods += [Pod(requests=Resources(cpu=24, memory="48Gi")) for _ in range(10)]
        prob = compile_problem(pods, [small, big], inventory)
        fused = assert_parity(prob)
        assert fused.leftover[: len(prob.classes)].sum() == 0

    def test_unsupported_raises(self, setup):
        env, pool, types = setup
        # more signatures than the VMEM state holds
        pods = [
            Pod(
                requests=Resources(cpu=1),
                node_selector={"custom-label": f"v{i}"},
            )
            for i in range(pallas_packer.S_MAX + 1)
        ]
        prob = compile_problem(pods, [pool], {pool.name: types})
        with pytest.raises(ValueError, match="signatures"):
            pallas_packer.run_pack_pallas(prob)
