"""Pallas packer parity: the fused kernel must match the scan kernel
placement-for-placement (conftest runs it through the interpreter on the
virtual CPU mesh; bench runs it compiled on the real chip)."""

import numpy as np
import pytest

from karpenter_tpu.api import Pod, Requirement, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.ops import pallas_packer
from karpenter_tpu.ops.packer import run_pack
from karpenter_tpu.ops.tensorize import compile_problem
from karpenter_tpu.testing import Environment


@pytest.fixture(scope="module")
def setup():
    env = Environment()
    pool = env.default_node_pool()
    nc = env.default_node_class()
    types = env.instance_types.list(pool, nc)
    return env, pool, types


def assert_parity(prob, objective="nodes"):
    scan = run_pack(prob, objective=objective)
    fused = pallas_packer.run_pack_pallas(prob, objective=objective)
    scan_take = np.asarray(scan.take)
    G = len(prob.classes)
    # per-class totals and per-slot aggregate placements must agree
    np.testing.assert_array_equal(
        scan_take[:G].sum(axis=1), fused.take[:G].sum(axis=1)
    )
    np.testing.assert_array_equal(
        np.asarray(scan.leftover)[:G], fused.leftover[:G]
    )
    ks = min(scan_take.shape[1], fused.take.shape[1])
    np.testing.assert_array_equal(
        scan_take[:G, :ks], fused.take[:G, :ks]
    )
    np.testing.assert_array_equal(
        np.asarray(scan.node_cfg)[:ks][np.asarray(scan.node_pods)[:ks] > 0],
        fused.node_cfg[:ks][fused.node_pods[:ks] > 0],
    )
    return fused


class TestPallasParity:
    def test_homogeneous(self, setup):
        env, pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(300)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert pallas_packer.supports(prob)
        assert_parity(prob)

    def test_heterogeneous_classes(self, setup):
        env, pool, types = setup
        sizes = [
            Resources(cpu=0.25, memory="512Mi"),
            Resources(cpu=1, memory="2Gi"),
            Resources(cpu=2, memory="4Gi"),
            Resources(cpu=4, memory="16Gi"),
        ]
        pods = [Pod(requests=sizes[i % 4]) for i in range(400)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert_parity(prob)

    def test_cost_objective(self, setup):
        env, pool, types = setup
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(200)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert_parity(prob, objective="cost")

    def test_anti_affinity_cap(self, setup):
        env, pool, types = setup
        sel = (("app", "d"),)
        pods = [
            Pod(
                labels={"app": "d"},
                requests=Resources(cpu=0.25),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME, label_selector=sel,
                        anti=True,
                    )
                ],
            )
            for _ in range(50)
        ]
        prob = compile_problem(pods, [pool], {pool.name: types})
        fused = assert_parity(prob)
        assert (fused.take[: len(prob.classes)].max(axis=0) <= 1).all()

    def test_zone_spread_split(self, setup):
        env, pool, types = setup
        sel = (("app", "z"),)
        pods = [
            Pod(
                labels={"app": "z"},
                requests=Resources(cpu=1, memory="1Gi"),
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1, topology_key=L.LABEL_ZONE, label_selector=sel
                    )
                ],
            )
            for _ in range(60)
        ]
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert_parity(prob)

    def test_mixed_constraints(self, setup):
        env, pool, types = setup
        pods = []
        for i in range(150):
            p = Pod(requests=Resources(cpu=[0.5, 1, 2][i % 3], memory="1Gi"))
            if i % 4 == 0:
                p.node_selector = {L.LABEL_ARCH: "arm64"}
            if i % 5 == 0:
                p.required_affinity = [
                    Requirement(L.LABEL_ZONE, Op.IN, ["zone-a", "zone-b"])
                ]
            pods.append(p)
        prob = compile_problem(pods, [pool], {pool.name: types})
        assert_parity(prob)

    def test_existing_nodes_prefill(self, setup):
        env, pool, types = setup
        from karpenter_tpu.state.cluster import StateNode

        existing = [
            StateNode(
                name=f"node-{i}",
                provider_id=f"i-{i}",
                labels={
                    L.LABEL_ARCH: "amd64",
                    L.LABEL_OS: "linux",
                    L.LABEL_ZONE: "zone-a",
                },
                taints=[],
                allocatable=Resources(cpu=8, memory="32Gi", pods=110),
            )
            for i in range(3)
        ]
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(20)]
        prob = compile_problem(
            pods, [pool], {pool.name: types}, existing=existing
        )
        fused = assert_parity(prob)
        # existing slots filled first
        assert fused.take[:, :3].sum() > 0

    def test_pool_priority_splits_signature_rows(self, setup):
        """Classes sharing a constraint signature can carry DIFFERENT
        feasibility rows (the pool-weight priority pass restricts per
        class by request size) — the fused kernel must not collapse them
        onto one admission row.  Regression: small pods restricted to a
        high-weight small-instance pool once masked the big pods' only
        feasible (low-weight) pool, leaving them unschedulable."""
        from karpenter_tpu.api import Requirements
        from karpenter_tpu.testing import Environment

        env = Environment()
        nc = env.default_node_class()
        small = env.default_node_pool(
            name="small",
            weight=100,
            requirements=Requirements(
                [Requirement(L.LABEL_INSTANCE_CPU, Op.LT, ["9"])]
            ),
        )
        big = env.default_node_pool(
            name="big",
            weight=0,
            requirements=Requirements(
                [Requirement(L.LABEL_INSTANCE_CPU, Op.GT, ["31"])]
            ),
        )
        inventory = {
            "small": env.instance_types.list(small, nc),
            "big": env.instance_types.list(big, nc),
        }
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(30)]
        pods += [Pod(requests=Resources(cpu=24, memory="48Gi")) for _ in range(10)]
        prob = compile_problem(pods, [small, big], inventory)
        fused = assert_parity(prob)
        assert fused.leftover[: len(prob.classes)].sum() == 0

    def test_unsupported_raises(self, setup):
        env, pool, types = setup
        # more signatures than the VMEM state holds
        pods = [
            Pod(
                requests=Resources(cpu=1),
                node_selector={"custom-label": f"v{i}"},
            )
            for i in range(pallas_packer.S_MAX + 1)
        ]
        prob = compile_problem(pods, [pool], {pool.name: types})
        with pytest.raises(ValueError, match="signatures"):
            pallas_packer.run_pack_pallas(prob)


class TestDispatchCrossover:
    """auto_pack's kernel choice must match the MEASURED crossover model
    (pallas_packer's calibrated fixed-overhead / per-step-gain constants),
    not an arbitrary constant: production never dispatching the fused
    kernel is the calibrated regime — config 2's ~320 classes sit well
    below the ~900-step break-even."""

    def test_threshold_derives_from_measured_constants(self):
        from karpenter_tpu.ops.packer import _bucket

        crossover = pallas_packer.pallas_crossover_classes()
        assert crossover == int(
            pallas_packer.PALLAS_FIXED_OVERHEAD_MS
            * 1000.0
            / pallas_packer.PALLAS_PER_STEP_GAIN_US
        )
        # threshold = break-even rounded to the class-axis compile bucket
        assert pallas_packer.PALLAS_MIN_CLASSES == _bucket(crossover)
        assert pallas_packer.PALLAS_MIN_CLASSES >= crossover

    def _many_class_problem(self, setup, n_classes):
        """A problem with a deep class axis but few signatures, the shape
        supports() admits (config 2's structure, stretched)."""
        env, pool, types = setup
        pods = [
            Pod(requests=Resources(cpu=0.01 * (1 + i), memory="64Mi"))
            for i in range(n_classes)
        ]
        return compile_problem(pods, [pool], {pool.name: types})

    def test_config2_scale_dispatches_scan_on_tpu(self, setup):
        """~320 classes (the production deep-class shape) is BELOW the
        crossover: even on a TPU, auto_pack must pick the scan kernel —
        VERDICT r5's 'production never dispatches Pallas' is by design."""
        prob = self._many_class_problem(setup, 320)
        assert len(prob.classes) >= 256
        assert len(prob.classes) < pallas_packer.PALLAS_MIN_CLASSES
        assert pallas_packer.choose_kernel(prob, platform="tpu") == "scan"

    def test_beyond_crossover_dispatches_pallas_on_tpu(self, setup):
        prob = self._many_class_problem(
            setup, pallas_packer.PALLAS_MIN_CLASSES
        )
        assert len(prob.classes) >= pallas_packer.PALLAS_MIN_CLASSES
        assert pallas_packer.supports(prob)
        assert pallas_packer.choose_kernel(prob, platform="tpu") == "pallas"
        # ...but never off-TPU (the interpreter is a correctness tool)
        assert pallas_packer.choose_kernel(prob, platform="cpu") == "scan"

    def test_auto_pack_records_choice(self, setup):
        prob = self._many_class_problem(setup, 16)
        pallas_packer.auto_pack(prob)
        assert pallas_packer.LAST_KERNEL == pallas_packer.choose_kernel(prob)
