"""Multi-tenant SolverService proofs (docs/designs/solver-service.md):

- **twin**: a tenant solving through the shared service — batched path
  FORCED by concurrent peers — gets bit-identical placements to the
  same problems through a dedicated single-tenant sidecar (mismatch
  count 0, batched count nonzero);
- **fairness**: a tenant flooding at 10x gets a bounded share of every
  weighted-round-robin drain; a refused tenant's backpressure carries a
  machine-readable retry-after hint; cold tenants' resident tensors are
  evicted before the device budget is exceeded;
- **lifecycle**: ``stop()`` severs established handler connections (the
  zombie-handler regression the store server fixed), and solve RPCs
  adopt the CLIENT's trace ID so cross-process tick timelines stitch;
- **fleet**: the ``solver-fleet`` sim scenario — a dozen real Operators,
  each a tenant of ONE service — holds the chaos-suite invariants with
  zero refusals, and (slow) run/run + run/replay byte-identity.
"""

import logging
import threading
from collections import deque

import numpy as np
import pytest

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.batcher.core import WeightedRoundRobin
from karpenter_tpu.obs.context import trace_context
from karpenter_tpu.ops.packer import run_pack
from karpenter_tpu.ops.tensorize import compile_problem
from karpenter_tpu.service import (
    RemoteSolver,
    SolverBusyError,
    SolverServer,
    SolverUnavailableError,
)
from karpenter_tpu.service.server import _TenantStats
from karpenter_tpu.testing import Environment


def _problems(counts_or_cpus, n_pods=None):
    """Compile one problem per entry: same pool/types (same shapes, so
    the service groups them into ONE batch bucket) with per-entry pod
    CPU when ``n_pods`` is set, else per-entry pod counts."""
    env = Environment()
    pool = env.default_node_pool()
    env.default_node_class()
    types = env.instance_types.list(pool, env.kube.get_node_class("default"))
    out = {}
    for x in counts_or_cpus:
        if n_pods is None:
            pods = [
                Pod(requests=Resources(cpu=1, memory="1Gi"))
                for _ in range(x)
            ]
        else:
            pods = [
                Pod(requests=Resources(cpu=x, memory="1Gi"))
                for _ in range(n_pods)
            ]
        out[x] = compile_problem(pods, [pool], {pool.name: types})
    return out


# ------------------------------------------------------------- fairness
class TestWeightedRoundRobin:
    def test_flooding_tenant_gets_bounded_share(self):
        """A tenant queueing 10x the work of its peers gets at most its
        fair share of every drain — the weighted-RR invariant admission
        leans on."""
        wrr = WeightedRoundRobin()
        queues = {
            "flood": deque(f"f{i}" for i in range(100)),
            "a": deque(f"a{i}" for i in range(10)),
            "b": deque(f"b{i}" for i in range(10)),
        }
        picked = wrr.drain(queues, 15)
        share = [name for name, _ in picked].count("flood")
        assert share == 5, picked  # exactly limit/3: no flood advantage
        # and over repeated drains the peers fully drain while the
        # flooder is still being paced
        while queues["a"] or queues["b"]:
            wrr.drain(queues, 15)
        assert queues["flood"]

    def test_weights_shift_the_share(self):
        wrr = WeightedRoundRobin()
        queues = {
            "gold": deque(range(100)),
            "bronze": deque(range(100)),
        }
        picked = wrr.drain(queues, 12, weights={"gold": 3.0, "bronze": 1.0})
        gold = [name for name, _ in picked].count("gold")
        assert gold == 9  # 3:1 split of 12

    def test_drain_order_is_deterministic(self):
        def run():
            wrr = WeightedRoundRobin()
            queues = {
                "c": deque(range(5)), "a": deque(range(5)),
                "b": deque(range(5)),
            }
            return [name for name, _ in wrr.drain(queues, 15)]

        assert run() == run()


class TestBackpressure:
    def test_refusal_carries_retry_after_hint(self):
        """A tenant at its in-flight cap is refused EXPLICITLY — the
        client raises SolverBusyError with the server's retry-after
        hint, never a silent queue slot."""
        srv = SolverServer(port=0, multi_tenant=True, inflight_cap=2)
        srv.start_background()
        try:
            # deterministically saturate the tenant: in-flight at cap
            with srv._cv:
                ts = srv._tenants["full"] = _TenantStats("full")
                ts.inflight = srv.inflight_cap
            prob = _problems([3])[3]
            c = RemoteSolver(*srv.address, tenant="full")
            try:
                with pytest.raises(SolverBusyError) as exc:
                    c.pack_problem(prob)
            finally:
                c.close()
            assert exc.value.reason == "inflight-cap"
            assert exc.value.retry_after_s > 0
            assert srv.registry.counter(
                "karpenter_service_refusals_total",
                {"tenant": "full", "reason": "inflight-cap"},
            ) == 1
            # the refusal is a ledger fact on the tenant's slice
            refused = [
                ev for ev in srv.ledger.recent()
                if ev.type == "TenantRefused"
            ]
            assert refused and refused[0].attrs["tenant"] == "full"
            assert float(refused[0].attrs["retry_after_s"]) > 0
            # an unsaturated tenant still solves
            c2 = RemoteSolver(*srv.address, tenant="fine")
            try:
                out = c2.pack_problem(prob)
            finally:
                c2.close()
            np.testing.assert_array_equal(
                out.take, np.asarray(run_pack(prob).take)
            )
        finally:
            srv.stop()

    def test_cold_tenant_evicted_before_budget_exceeded(self):
        """With a budget sized for ~2 tenants, a third tenant's upload
        drops the COLDEST tenant's resident tensors — the pool never
        ends a solve over budget, and the eviction is counted + led."""
        srv = SolverServer(
            port=0, multi_tenant=True, resident_budget_mb=0.2
        )
        srv.start_background()
        try:
            prob = _problems([3])[3]
            expected = np.asarray(run_pack(prob).take)
            for tenant in ("t-0", "t-1", "t-2", "t-3"):
                c = RemoteSolver(*srv.address, tenant=tenant)
                try:
                    out = c.pack_problem(prob)
                finally:
                    c.close()
                # eviction never corrupts a solve
                np.testing.assert_array_equal(out.take, expected)
                with srv._pool_lock:
                    assert (
                        srv._pool.total_bytes() <= srv._pool.budget_bytes
                    )
            evicted = [
                ev.attrs["tenant"]
                for ev in srv.ledger.recent()
                if ev.type == "TenantEvicted"
            ]
            assert "t-0" in evicted  # the coldest tenant went first
            assert srv.registry.counter(
                "karpenter_service_resident_evictions_total",
                {"tenant": "t-0"},
            ) >= 1
            # the survivors' footprint is still reported per tenant
            payload = srv.tenants_payload()
            assert payload["resident_budget_bytes"] > 0
            resident = {
                t: d.get("resident_bytes", 0)
                for t, d in payload["tenants"].items()
            }
            assert resident.get("t-3", 0) > 0
            assert resident.get("t-0", 0) == 0
        finally:
            srv.stop()


# ----------------------------------------------------------------- twin
class TestTwin:
    def test_batched_tenant_bit_identical_to_dedicated_sidecar(self):
        """THE tentpole proof: tenants solving through the shared
        service — including through the BATCHED vmap path — get
        placements bit-identical to a dedicated single-tenant sidecar.
        Mismatch count must be 0 and the batched path must actually
        have run (a twin proof that only exercised the solo
        fall-through proves nothing about batching)."""
        tenants = ["acme", "globex", "initech", "umbrella"]
        probs = _problems([0.5, 1, 2, 4], n_pods=24)
        by_tenant = dict(zip(tenants, probs.values()))

        sidecar = SolverServer(port=0)  # the dedicated twin, legacy mode
        sidecar.start_background()
        expected = {}
        try:
            for tenant, prob in by_tenant.items():
                c = RemoteSolver(*sidecar.address)
                try:
                    expected[tenant] = c.pack_problem(prob)
                finally:
                    c.close()
        finally:
            sidecar.stop()

        srv = SolverServer(port=0, multi_tenant=True)
        srv.start_background()
        mismatches = 0
        errors = []
        try:
            for _round in range(10):
                barrier = threading.Barrier(len(tenants))
                results = {}

                def worker(tenant):
                    try:
                        c = RemoteSolver(*srv.address, tenant=tenant)
                        try:
                            barrier.wait(timeout=30)
                            results[tenant] = c.pack_problem(
                                by_tenant[tenant]
                            )
                        finally:
                            c.close()
                    except Exception as exc:  # pragma: no cover
                        errors.append((tenant, exc))

                threads = [
                    threading.Thread(target=worker, args=(t,))
                    for t in tenants
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                assert not errors, errors
                for tenant in tenants:
                    twin = expected[tenant]
                    got = results[tenant]
                    for field in (
                        "take", "leftover", "node_cfg", "node_pods",
                        "node_used",
                    ):
                        if not np.array_equal(
                            getattr(got, field), getattr(twin, field)
                        ):
                            mismatches += 1
                batched = sum(
                    t["batched"]
                    for t in srv.tenants_payload()["tenants"].values()
                )
                if batched > 0 and _round >= 2:
                    break
            assert mismatches == 0
            assert batched > 0, "batched path never ran — no twin proof"
            # the fused dispatches are ledger facts naming their tenants
            fleet_batches = [
                ev for ev in srv.ledger.recent()
                if ev.type == "TenantBatch"
            ]
            assert fleet_batches
            assert int(fleet_batches[0].attrs["size"]) >= 2
        finally:
            srv.stop()


# ------------------------------------------------------------ lifecycle
class TestLifecycle:
    def test_stop_severs_established_connections(self):
        """Regression: ``stop()`` must kill live handler connections,
        not just the accept loop — a pre-stop client with an
        established socket must not keep getting answers from a
        zombie handler thread."""
        srv = SolverServer(port=0).start_background()
        c = RemoteSolver(*srv.address)
        try:
            assert c.ping()  # establish the connection
            srv.stop()
            with pytest.raises(SolverUnavailableError):
                c.ping()
        finally:
            c.close()

    def test_solve_rpc_adopts_client_trace_id(self):
        """The server's handling span records under the CALLER's tick
        trace ID — cross-process timelines stitch on one ID."""
        srv = SolverServer(port=0, multi_tenant=True).start_background()
        try:
            prob = _problems([3])[3]
            c = RemoteSolver(*srv.address, tenant="traced")
            try:
                with trace_context("tick-004242"):
                    c.pack_problem(prob)
            finally:
                c.close()
            spans = [
                s for s in srv.tracer.recent(500)
                if s.path.startswith("solver.pack")
            ]
            assert spans
            assert spans[-1].trace_id == "tick-004242"
            assert spans[-1].meta.get("tenant") == "traced"
        finally:
            srv.stop()


# ---------------------------------------------------------------- fleet
TICKS = 24
OPERATORS = 12


@pytest.fixture(scope="module")
def solver_fleet_run():
    from karpenter_tpu.sim.fleet import run_fleet

    logging.disable(logging.WARNING)
    try:
        runner, report = run_fleet(
            "solver-fleet", 0, TICKS, operators=OPERATORS
        )
    finally:
        logging.disable(logging.NOTSET)
    return runner, report


class TestSolverFleet:
    def test_scenario_registered(self):
        from karpenter_tpu.sim.fleet import FLEET_SCENARIOS

        assert "solver-fleet" in FLEET_SCENARIOS

    def test_fleet_shares_one_service_cleanly(self, solver_fleet_run):
        """A dozen real Operators, each a tenant of ONE SolverService:
        chaos-suite invariants hold (zero double-launches, clean
        violations) and the service refused nobody."""
        _runner, report = solver_fleet_run
        assert report["operators"] == OPERATORS
        assert report["double_launches"] == 0
        assert report["invariants"]["violations"] == []
        assert report["launches"] > 0
        solver = report["solver"]
        assert solver["multi_tenant"] is True
        assert solver["refused"] == 0
        # leaders rotated through the storm, so MULTIPLE operator
        # tenants solved against the one mesh
        assert len(solver["tenants"]) >= 2
        assert sum(solver["solves_by_tenant"].values()) > 0

    @pytest.mark.slow
    def test_run_run_byte_identical(self, solver_fleet_run):
        from karpenter_tpu.sim.fleet import run_fleet

        runner, report = solver_fleet_run
        logging.disable(logging.WARNING)
        try:
            runner2, report2 = run_fleet(
                "solver-fleet", 0, TICKS, operators=OPERATORS
            )
        finally:
            logging.disable(logging.NOTSET)
        assert report2 == report
        assert runner2.trace.text() == runner.trace.text()

    @pytest.mark.slow
    def test_replay_byte_identical(self, solver_fleet_run, tmp_path):
        from karpenter_tpu.sim.fleet import read_fleet_tape, replay_fleet

        runner, report = solver_fleet_run
        path = tmp_path / "solver-fleet.jsonl"
        path.write_text(runner.trace.text())
        logging.disable(logging.WARNING)
        try:
            runner3, report3, recorded = replay_fleet(str(path))
        finally:
            logging.disable(logging.NOTSET)
        assert recorded == report
        assert report3 == report
        assert runner3.trace.text() == runner.trace.text()
        meta = read_fleet_tape(str(path))[0]
        assert meta["scenario"] == "solver-fleet"
        assert meta["operators"] == OPERATORS
