"""Fleet-scale store plane (docs/designs/store-scale.md).

Covers the four legs of the new plane: (1) the negotiated binary codec —
round-trip parity with tagged JSON on every wire object, and a binary
client against a JSON-only (pre-fleet-scale) server negotiating down
cleanly; (2) delta watch resync — a reconnecting client presents its
last seq and receives only the gap, falling back to a snapshot once
compaction passed it; (3) backpressured fan-out — a deliberately wedged
watch client is coalesced onto a forced resync while healthy clients
keep streaming (the unbounded-queue regression); (4) compaction bounds
and the read replica's rv-ordering guarantee.
"""

import random
import socket
import threading
import time

import pytest

from karpenter_tpu.api import (
    NodeClaim,
    NodeClass,
    NodePool,
    Pod,
    Resources,
)
from karpenter_tpu.api.objects import (
    PodAffinityTerm,
    SelectorTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.api.requirements import Op, Requirement
from karpenter_tpu.service.codec import (
    CODEC_BIN,
    CODEC_JSON,
    decode_payload,
    encode_payload,
    recv_frame,
    send_frame,
)
from karpenter_tpu.service.store_server import StoreServer, VersionedStore
from karpenter_tpu.state.binwire import (
    SCHEMA_FP,
    decode_value,
    encode_value,
)
from karpenter_tpu.state.kube import KubeStore, Node
from karpenter_tpu.state.remote import RemoteKubeStore
from karpenter_tpu.state.wire import canonical, from_wire, to_wire

import json


def _json_round_trip(obj):
    return from_wire(json.loads(json.dumps(to_wire(obj))))


def _bin_round_trip(obj):
    return decode_value(encode_value(obj))


def _seeded_pod(rng: random.Random) -> Pod:
    pod = Pod(
        name=f"p{rng.randrange(10_000)}",
        requests=Resources(
            cpu=rng.choice([0.25, 1, 3]), memory=f"{rng.randrange(1, 9)}Gi"
        ),
        labels={f"k{i}": f"v{rng.randrange(5)}" for i in range(rng.randrange(3))},
        node_selector=(
            {"zone": rng.choice(["a", "b"])} if rng.random() < 0.4 else {}
        ),
        phase=rng.choice(["Pending", "Running"]),
    )
    if rng.random() < 0.4:
        pod.required_affinity = [
            Requirement("z", Op.IN, ["z1", "z2"]),
            Requirement("gen", Op.GT, ["2"]),
        ]
    if rng.random() < 0.3:
        pod.tolerations = [Toleration(key="t", value="v")]
    if rng.random() < 0.3:
        pod.topology_spread = [
            TopologySpreadConstraint(1, "zone", label_selector=(("x", "y"),))
        ]
    if rng.random() < 0.2:
        pod.pod_affinity = [
            PodAffinityTerm(
                topology_key="kubernetes.io/hostname",
                anti=True,
                label_selector=(("x", "y"),),
            )
        ]
    return pod


def _wire_corpus():
    rng = random.Random(7)
    yield NodeClass(
        name="default",
        subnet_selector_terms=[SelectorTerm.of(Name="*")],
        security_group_selector_terms=[SelectorTerm.of(Name="*", Tag="x")],
    )
    yield NodePool(name="np", node_class_ref="default", weight=7)
    yield Node(
        name="n1",
        ready=True,
        labels={"a": "b"},
        taints=[Taint("k", "v", "NoSchedule")],
        capacity=Resources(cpu=8, memory="32Gi"),
        allocatable=Resources(cpu=7.5, memory="30Gi"),
    )
    yield NodeClaim(name="c1", labels={"x": "y"}, provider_id="i-123")
    from karpenter_tpu.state.kube import PodDisruptionBudget

    yield PodDisruptionBudget(
        name="pdb", label_selector={"app": "web"}, min_available=1
    )
    from karpenter_tpu.api import PersistentVolumeClaim, StorageClass

    yield StorageClass(name="sc", zones=("zone-a",))
    yield PersistentVolumeClaim(name="pvc", storage_class="sc")
    from karpenter_tpu.utils.leader import Lease

    yield Lease(name="lead", holder="a", renewed_at=12.5, duration_s=15.0)
    for _ in range(40):
        yield _seeded_pod(rng)


class TestBinCodecParity:
    def test_every_wire_object_round_trips_identically(self):
        """Fuzz parity: for every store-protocol object, the binary and
        JSON codecs decode to canonical-equal values."""
        for obj in _wire_corpus():
            via_json = _json_round_trip(obj)
            via_bin = _bin_round_trip(obj)
            assert canonical(via_bin) == canonical(obj), type(obj).__name__
            assert canonical(via_bin) == canonical(via_json)

    def test_scalars_and_containers(self):
        for v in (
            None, True, False, 0, -1, 2**70, -(2**70), 1.5, -0.0, "", "héllo",
            [1, "a", None], (1, 2), frozenset({"b", "a"}),
            {"k": {"nested": [1.25]}},
        ):
            rt = _bin_round_trip(v)
            assert rt == v and type(rt) is type(v)
            if isinstance(v, float):
                assert repr(rt) == repr(v)  # -0.0 survives

    def test_default_elision_shrinks_the_wire(self):
        pod = Pod(requests=Resources(cpu=1, memory="2Gi"))
        assert len(encode_value(pod)) < len(
            json.dumps(to_wire(pod)).encode()
        ) / 4

    def test_unknown_class_id_and_trailing_bytes_refuse(self):
        with pytest.raises(ValueError, match="unknown bin1 class id"):
            decode_value(bytes([13, 250, 1, 0]))
        with pytest.raises(ValueError, match="trailing"):
            decode_value(encode_value(1) + b"\x00")

    def test_schema_fp_covers_field_defaults(self):
        """Elision round-trips through declared defaults, so a build
        whose DEFAULTS drifted (names unchanged) must fingerprint
        differently and negotiate down to JSON — otherwise an elided
        field would silently decode to the wrong value."""
        import dataclasses as dc

        from karpenter_tpu.state.binwire import _build_tables

        @dc.dataclass
        class Thing:
            name: str
            ready: bool = False

        @dc.dataclass
        class ThingFlipped:
            name: str
            ready: bool = True

        ThingFlipped.__name__ = "Thing"  # same names, drifted default
        _c1, _i1, fp1 = _build_tables((Thing,))
        _c2, _i2, fp2 = _build_tables((ThingFlipped,))
        assert fp1 != fp2

    def test_payload_layer_versioned(self):
        payload = encode_payload({"method": "ping"}, CODEC_BIN)
        assert decode_payload(payload, CODEC_BIN) == {"method": "ping"}
        with pytest.raises(ValueError, match="magic"):
            decode_payload(b"\x00\x01\x00", CODEC_BIN)
        with pytest.raises(ValueError, match="version"):
            decode_payload(bytes([0xB5, 99]) + b"\x00", CODEC_BIN)


# --------------------------------------------------------------- harness
@pytest.fixture
def server():
    srv = StoreServer().start_background()
    yield srv
    srv.stop()


def _client(server, **kw):
    host, port = server.address
    return RemoteKubeStore(host, port, **kw)


def _default_objects(kube):
    kube.put_node_class(
        NodeClass(
            name="default",
            subnet_selector_terms=[SelectorTerm.of(Name="*")],
            security_group_selector_terms=[SelectorTerm.of(Name="*")],
        )
    )
    kube.put_node_pool(NodePool(name="default", node_class_ref="default"))


def _raw_watch(
    server,
    identity="raw",
    since_seq=0,
    codecs=(CODEC_BIN, CODEC_JSON),
    epoch=None,
):
    """A protocol-level watch: returns (sock, ack, first_frame, codec).
    Presents the server's own epoch by default — a cursor is only
    meaningful inside the seq space it came from."""
    sock = socket.create_connection(server.address, timeout=5.0)
    send_frame(
        sock,
        encode_payload(
            {
                "method": "watch",
                "identity": identity,
                "codecs": list(codecs),
                "schema_fp": SCHEMA_FP,
                "since_seq": since_seq,
                "epoch": server.store.epoch if epoch is None else epoch,
            },
            CODEC_JSON,
        ),
    )
    sock.settimeout(5.0)
    ack = decode_payload(recv_frame(sock), CODEC_JSON)
    codec = ack.get("codec", CODEC_JSON)
    first = decode_payload(recv_frame(sock), codec)
    return sock, ack, first, codec


def _wait(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


class TestNegotiation:
    def test_rpc_connection_negotiates_bin(self, server):
        a = _client(server, identity="a", start_watch=False)
        try:
            a.put_pod(Pod(name="p", requests=Resources(cpu=1)))
            assert a._sock_codec == CODEC_BIN
            assert server.store.kube.pods["default/p"].requests.cpu == 1
        finally:
            a.close()

    def test_json_pref_never_negotiates(self, server):
        a = _client(server, identity="a", start_watch=False, codec="json")
        try:
            a.put_pod(Pod(name="p", requests=Resources(cpu=1)))
            assert a._sock_codec == CODEC_JSON
        finally:
            a.close()

    def test_binary_client_against_legacy_server_negotiates_down(self):
        """The mixed-version session: a binary-preferring client against
        the pre-fleet-scale protocol (no hello, inline-snapshot watch)
        stays fully functional over tagged JSON."""
        srv = StoreServer(legacy_protocol=True).start_background()
        a = b = None
        try:
            a = _client(srv, identity="a")
            b = _client(srv, identity="b")
            assert a.wait_synced()
            _default_objects(a)
            a.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
            assert a._sock_codec == CODEC_JSON
            assert b.wait_synced()
            assert "default/p1" in b.pods
            a.delete_pod("default/p1")
            assert b.wait_synced()
            assert "default/p1" not in b.pods
        finally:
            for c in (a, b):
                if c is not None:
                    c.close()
            srv.stop()

    def test_schema_fp_mismatch_falls_back_to_json(self, server):
        sock = socket.create_connection(server.address, timeout=5.0)
        try:
            send_frame(
                sock,
                encode_payload(
                    {
                        "method": "hello",
                        "codecs": [CODEC_BIN, CODEC_JSON],
                        "schema_fp": "someone-elses-build",
                    },
                    CODEC_JSON,
                ),
            )
            sock.settimeout(5.0)
            response = decode_payload(recv_frame(sock), CODEC_JSON)
            assert response["status"] == "ok"
            assert response["codec"] == CODEC_JSON
        finally:
            sock.close()


class TestDeltaResync:
    def test_reconnect_replays_only_the_gap(self, server):
        a = _client(server, identity="a", start_watch=False)
        try:
            _default_objects(a)
            for i in range(8):
                a.put_pod(Pod(name=f"p{i}", requests=Resources(cpu=1)))
            seen_seq = server.store.log_seq
            for i in range(8, 18):
                a.put_pod(Pod(name=f"p{i}", requests=Resources(cpu=1)))
            sock, ack, first, codec = _raw_watch(server, since_seq=seen_seq)
            try:
                assert ack["resync"] == "replay"
                assert first["type"] == "resync" and first["mode"] == "replay"
                keys = [
                    ev["key"] for ev in first["events"] if "key" in ev
                ]
                assert keys == [f"default/p{i}" for i in range(8, 18)]
                # rv ordering preserved in the replayed gap
                rvs = [ev["rv"] for ev in first["events"]]
                assert rvs == sorted(rvs)
            finally:
                sock.close()
            assert (
                server.registry.counter(
                    "karpenter_store_resync_total", {"kind": "replay"}
                )
                >= 1
            )
        finally:
            a.close()

    def test_compacted_gap_falls_back_to_snapshot(self):
        store = VersionedStore(replay_log_events=5)
        srv = StoreServer(store=store).start_background()
        a = None
        try:
            a = _client(srv, identity="a", start_watch=False)
            _default_objects(a)
            a.put_pod(Pod(name="p0", requests=Resources(cpu=1)))
            seen_seq = store.log_seq
            for i in range(1, 20):  # blows past the 5-event replay log
                a.put_pod(Pod(name=f"p{i}", requests=Resources(cpu=1)))
            assert store.compacted_seq > seen_seq
            sock, ack, first, codec = _raw_watch(srv, since_seq=seen_seq)
            try:
                assert ack["resync"] == "snapshot"
                assert first["mode"] == "snapshot"
                assert len(first["snapshot"]["kinds"]["Pod"]) == 20
            finally:
                sock.close()
            assert (
                srv.registry.counter(
                    "karpenter_store_resync_total", {"kind": "snapshot"}
                )
                >= 1
            )
            assert (
                srv.registry.counter(
                    "karpenter_store_compactions_total", {"log": "replay"}
                )
                >= 1
            )
        finally:
            if a is not None:
                a.close()
            srv.stop()

    def test_live_client_heals_through_delta_resync(self, server):
        """Kill a mirror's watch socket mid-stream: the reconnect
        presents since_seq and the server replays just the gap — the
        mirror converges without a full snapshot."""
        a = _client(server, identity="a", start_watch=False)
        b = _client(server, identity="b")
        try:
            _default_objects(a)
            a.put_pod(Pod(name="p0", requests=Resources(cpu=1)))
            assert b.wait_synced()
            assert b._watch_seq > 0
            # sever b's stream, then write while it is dark
            _wait(lambda: b._watch_sock is not None, msg="watch socket")
            b._watch_sock.close()
            for i in range(1, 6):
                a.put_pod(Pod(name=f"p{i}", requests=Resources(cpu=1)))
            assert b.wait_synced(timeout=10.0)
            assert set(b.pods) == set(server.store.kube.pods)
            _wait(
                lambda: server.registry.counter(
                    "karpenter_store_resync_total", {"kind": "replay"}
                )
                >= 1,
                msg="server-side replay resync count",
            )
        finally:
            a.close()
            b.close()


class TestSeqEpochReset:
    def test_fresh_server_epoch_resets_the_resync_cursor(self):
        """Seq spaces are per-server: a store restarted over a FRESH
        VersionedStore starts a new epoch at 0.  The mirror must ADOPT
        the new epoch's seq from its snapshot resync (never max() it
        with the stale higher cursor), or a later reconnect would
        present an inflated since_seq and receive a wrong delta that
        silently skips events."""
        srv1 = StoreServer().start_background()
        host, port = srv1.address
        a = RemoteKubeStore(host, port, identity="writer", start_watch=False)
        b = RemoteKubeStore(host, port, identity="mirror")
        try:
            _default_objects(a)
            for i in range(30):
                a.put_pod(Pod(name=f"old{i}", requests=Resources(cpu=1)))
            assert b.wait_synced()
            stale_seq = b._watch_seq
            assert stale_seq >= 30
            a.close()
            srv1.stop()
            # a NEW store (fresh epoch) on the same address, pre-seeded
            # with different state (its log does not reach genesis for
            # the mirror's cursor — snapshot is the only honest sync)
            kube = KubeStore()
            kube.put_pod(Pod(name="fresh", requests=Resources(cpu=1)))
            srv2 = StoreServer(host, port, store=VersionedStore(kube))
            srv2.start_background()
            try:
                _wait(
                    lambda: "default/fresh" in b.pods
                    and "default/old0" not in b.pods,
                    timeout=10.0,
                    msg="mirror adoption of the new epoch's state",
                )
                # the cursor was ASSIGNED the new epoch's seq — not
                # maxed with the stale one
                _wait(
                    lambda: b._watch_seq <= srv2.store.log_seq,
                    msg="resync cursor reset to the new epoch",
                )
                assert b._watch_seq < stale_seq
                # and delta resync works from the NEW epoch: new events
                # flow, and the mirror tracks the new seq space
                w = RemoteKubeStore(
                    host, port, identity="writer2", start_watch=False
                )
                try:
                    w.put_pod(Pod(name="after", requests=Resources(cpu=1)))
                    assert b.wait_synced(timeout=10.0)
                    assert "default/after" in b.pods
                finally:
                    w.close()
            finally:
                srv2.stop()
        finally:
            b.close()


    def test_overtaken_cursor_from_another_epoch_still_snapshots(self):
        """The deeper epoch hazard: a fresh store whose NEW seq space
        has already OVERTAKEN the stale cursor would look 'covered' to a
        bare number — the epoch id in the handshake is what forces the
        honest snapshot instead of a wrong delta that silently skips the
        inter-epoch divergence."""
        srv1 = StoreServer().start_background()
        host, port = srv1.address
        a = RemoteKubeStore(host, port, identity="writer", start_watch=False)
        b = RemoteKubeStore(host, port, identity="mirror")
        try:
            _default_objects(a)
            for i in range(5):
                a.put_pod(Pod(name=f"old{i}", requests=Resources(cpu=1)))
            assert b.wait_synced()
            stale_seq = b._watch_seq
            old_epoch = b._watch_epoch
            assert stale_seq >= 5 and old_epoch
            a.close()
            srv1.stop()
            # fresh epoch whose log OVERTAKES the stale cursor and
            # reaches genesis — a bare since_seq would be "covered"
            srv2 = StoreServer(host, port).start_background()
            try:
                w = RemoteKubeStore(
                    host, port, identity="writer2", start_watch=False
                )
                try:
                    _default_objects(w)
                    for i in range(stale_seq + 5):
                        w.put_pod(
                            Pod(name=f"new{i}", requests=Resources(cpu=1))
                        )
                    assert srv2.store.covers(
                        stale_seq, srv2.store.epoch
                    ), "precondition: the bare number WOULD be covered"
                    assert not srv2.store.covers(stale_seq, old_epoch)
                    _wait(
                        lambda: "default/new0" in b.pods
                        and not any(k.startswith("default/old") for k in b.pods),
                        timeout=10.0,
                        msg="mirror snapshot-adoption across epochs",
                    )
                    assert b._watch_epoch == srv2.store.epoch
                    # the reconnect was served as a SNAPSHOT resync
                    _wait(
                        lambda: srv2.registry.counter(
                            "karpenter_store_resync_total",
                            {"kind": "snapshot"},
                        )
                        >= 1,
                        msg="snapshot resync counted",
                    )
                finally:
                    w.close()
            finally:
                srv2.stop()
        finally:
            b.close()


class TestBackpressure:
    def test_bounded_queue_overflow_coalesces(self):
        """The unbounded `_Subscriber` queue regression: a subscriber
        that never drains stops accumulating at the bound and flips to
        one pending resync, no matter how many more events land."""
        store = VersionedStore(watch_queue_batches=4)
        mode, payload, sub = store.subscribe("wedged", CODEC_JSON)
        kube = store.kube
        for i in range(50):
            store.mutate(
                lambda i=i: kube.put_pod(
                    Pod(name=f"p{i}", requests=Resources(cpu=1))
                )
            )
        assert len(sub.batches) == 0  # cleared on overflow
        assert sub.pending_resync
        assert sub.overflows >= 1
        # a healthy subscriber registered after the wedge still gets
        # every event
        mode2, payload2, sub2 = store.subscribe("healthy", CODEC_JSON)
        store.mutate(
            lambda: kube.put_pod(Pod(name="late", requests=Resources(cpu=1)))
        )
        assert len(sub2.batches) == 1
        assert (
            store.registry.gauge("karpenter_store_watch_queue_depth") <= 4
        )

    def test_wedged_socket_client_is_resynced_not_oomed(self):
        """End-to-end: a watch client that stops reading wedges its TCP
        stream; the server's bounded queue coalesces it onto a forced
        resync while a healthy mirror keeps streaming.  When the wedged
        client finally reads again, it receives a resync frame and ends
        up consistent."""
        store = VersionedStore(watch_queue_batches=4)
        srv = StoreServer(store=store).start_background()
        a = b = None
        wedged = None
        try:
            a = _client(srv, identity="a", start_watch=False)
            b = _client(srv, identity="b")
            _default_objects(a)
            # the wedged client: reads the initial snapshot, then stops.
            # A tiny receive buffer (set BEFORE connect, or it is
            # ignored) makes the server's sendall block fast.
            wedged = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            wedged.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
            wedged.settimeout(10.0)
            wedged.connect(srv.address)
            send_frame(
                wedged,
                encode_payload(
                    {
                        "method": "watch",
                        "identity": "wedged",
                        "codecs": [CODEC_JSON],
                        "schema_fp": SCHEMA_FP,
                        "since_seq": 0,
                    },
                    CODEC_JSON,
                ),
            )
            wedged.settimeout(10.0)
            decode_payload(recv_frame(wedged), CODEC_JSON)  # ack
            decode_payload(recv_frame(wedged), CODEC_JSON)  # snapshot
            # large objects so the kernel buffers fill fast
            blob = "x" * 262_144
            for i in range(40):
                a.put_pod(
                    Pod(
                        name=f"big{i}",
                        requests=Resources(cpu=1),
                        labels={"blob": blob},
                    )
                )
            # the wedged subscriber's queue overflowed and was coalesced
            # (its sender thread is still blocked in sendall, so the
            # resync COUNTER moves only once the client drains — the
            # bounded-memory guarantee is the flag + the cleared queue)
            def _wedged_overflowed():
                with store.lock:
                    return any(
                        s.identity == "wedged" and s.overflows >= 1
                        for s in store._subscribers
                    )

            _wait(_wedged_overflowed, timeout=10.0, msg="queue overflow")
            # server memory stayed bounded: no subscriber queue past cap
            with store.lock:
                assert all(
                    len(s.batches) <= 4 for s in store._subscribers
                )
            # the healthy mirror never stalled behind the wedged one
            assert b.wait_synced(timeout=10.0)
            assert len(b.pods) == 40
            # the wedged client resumes: drain until the resync marker
            wedged.settimeout(10.0)
            saw_resync = False
            for _ in range(200):
                frame = decode_payload(recv_frame(wedged), CODEC_JSON)
                if frame.get("type") == "resync":
                    saw_resync = True
                    assert frame["mode"] in ("replay", "snapshot")
                    break
            assert saw_resync
            _wait(
                lambda: srv.registry.counter(
                    "karpenter_store_resync_total", {"kind": "overflow"}
                )
                >= 1,
                msg="overflow resync counted once served",
            )
        finally:
            if wedged is not None:
                wedged.close()
            for c in (a, b):
                if c is not None:
                    c.close()
            srv.stop()


class TestCompaction:
    def test_mirror_ledger_bounded_for_own_events_too(self, server):
        """The mirror-side cap applies to events THIS client records,
        not only watch-absorbed foreign ones (the server's echo of an
        own event is skipped by the event_rv check, so the local append
        is the only copy that needs trimming)."""
        a = _client(server, identity="a", start_watch=False, events_cap=5)
        try:
            for i in range(12):
                a.record_event("Pod", "Created", f"p{i}", "msg")
            assert len(a.events) <= 5
            assert a.events[-1][2] == "p11"  # newest retained
        finally:
            a.close()

    def test_replicated_snapshot_resets_the_rv_map(self):
        """A snapshot has no tombstones: applying one must REPLACE the
        rv map (stale entries for keys the primary deleted would leave
        the mirror's rv bookkeeping diverged from what it serves)."""
        store = VersionedStore()
        store.rvs[("Pod", "default/ghost")] = 7  # pre-snapshot leftover
        donor = VersionedStore()
        donor.mutate(
            lambda: donor.kube.put_pod(
                Pod(name="real", requests=Resources(cpu=1))
            )
        )
        store.apply_replicated_snapshot(donor.snapshot())
        assert ("Pod", "default/ghost") not in store.rvs
        assert store.rvs == donor.rvs

    def test_replay_log_and_event_ledger_stay_bounded(self):
        store = VersionedStore(replay_log_events=10, events_cap=5)
        srv = StoreServer(store=store).start_background()
        a = None
        try:
            a = _client(srv, identity="a", start_watch=False)
            _default_objects(a)
            for i in range(40):
                a.put_pod(Pod(name=f"p{i}", requests=Resources(cpu=1)))
                a.record_event("Pod", "Created", f"p{i}", "msg")
            with store.lock:
                assert store._log_events <= 10 + 1
                assert store.compacted_seq > 0
                assert len(store.kube.events) <= 5
            assert (
                srv.registry.counter(
                    "karpenter_store_compactions_total", {"log": "events"}
                )
                >= 1
            )
            # snapshots ship only the retained ledger
            sock, ack, first, codec = _raw_watch(srv, identity="late")
            try:
                assert len(first["snapshot"]["events"]) <= 5
            finally:
                sock.close()
        finally:
            if a is not None:
                a.close()
            srv.stop()


class TestReadReplica:
    def test_replica_serves_snapshot_and_watch_with_primary_rv_order(self):
        primary = StoreServer().start_background()
        replica = StoreServer(replica_of=primary.address).start_background()
        a = r = None
        try:
            a = _client(primary, identity="writer", start_watch=False)
            _default_objects(a)
            for i in range(12):
                a.put_pod(Pod(name=f"p{i}", requests=Resources(cpu=1)))
            a.record_event("Pod", "Created", "p0", "hello")
            _wait(
                # cluster-event appends move event_rv, not rv — wait on
                # BOTH spaces or the ledger comparison below races the
                # Event batch still in flight
                lambda: len(replica.store.kube.pods) == 12
                and replica.store.rv >= primary.store.rv
                and replica.store.event_rv >= primary.store.event_rv,
                msg="replica convergence",
            )
            # the replica preserved the PRIMARY's rv numbers, key by key
            with primary.store.lock, replica.store.lock:
                assert replica.store.rvs == primary.store.rvs
                assert replica.store.rv == primary.store.rv
                assert [tuple(e) for e in replica.store.kube.events] == [
                    tuple(e) for e in primary.store.kube.events
                ]
            # a read client against the replica mirrors the same state
            r = _client(replica, identity="reader")
            assert r.wait_synced(timeout=10.0)
            assert set(r.pods) == set(a.pods)
            assert canonical(r.pods["default/p3"]) == canonical(
                a.pods["default/p3"]
            )
            # live updates flow primary -> replica -> reader, rv-ordered
            a.put_pod(Pod(name="fresh", requests=Resources(cpu=2)))
            _wait(
                lambda: "default/fresh" in r.pods, msg="replicated update"
            )
            assert (
                r._rvs[("Pod", "default/fresh")]
                == primary.store.rvs[("Pod", "default/fresh")]
            )
        finally:
            if r is not None:
                r.close()
            if a is not None:
                a.close()
            replica.stop()
            primary.stop()

    def test_replica_refuses_writes_and_names_the_primary(self):
        primary = StoreServer().start_background()
        replica = StoreServer(replica_of=primary.address).start_background()
        w = None
        try:
            w = _client(replica, identity="wrong-way", start_watch=False)
            with pytest.raises(RuntimeError, match="read-only replica"):
                w.put_pod(Pod(name="p", requests=Resources(cpu=1)))
            assert not primary.store.kube.pods
            assert not replica.store.kube.pods
        finally:
            if w is not None:
                w.close()
            replica.stop()
            primary.stop()

    def test_replica_follows_a_legacy_primary(self):
        """A read replica against a pre-fleet-scale primary (inline
        snapshot, seq-less event frames): the follower must keep
        replicating — a frame without a seq key must never kill the
        follower thread — with every reconnect honestly snapshotting
        (the legacy protocol has no delta space)."""
        primary = StoreServer(legacy_protocol=True).start_background()
        replica = StoreServer(replica_of=primary.address).start_background()
        a = None
        try:
            a = _client(primary, identity="writer", start_watch=False)
            _default_objects(a)
            for i in range(6):
                a.put_pod(Pod(name=f"p{i}", requests=Resources(cpu=1)))
            _wait(
                lambda: len(replica.store.kube.pods) == 6,
                timeout=10.0,
                msg="replication through the legacy stream",
            )
            # keep flowing: the follower thread survived the seq-less
            # frames (the KeyError-kills-the-thread regression)
            a.put_pod(Pod(name="late", requests=Resources(cpu=1)))
            _wait(
                lambda: "default/late" in replica.store.kube.pods,
                timeout=10.0,
                msg="continued replication",
            )
            with primary.store.lock, replica.store.lock:
                assert replica.store.rvs == primary.store.rvs
        finally:
            if a is not None:
                a.close()
            replica.stop()
            primary.stop()

    def test_replica_follower_delta_resyncs_after_disconnect(self):
        primary = StoreServer().start_background()
        replica = StoreServer(replica_of=primary.address).start_background()
        a = None
        try:
            a = _client(primary, identity="writer", start_watch=False)
            _default_objects(a)
            a.put_pod(Pod(name="p0", requests=Resources(cpu=1)))
            _wait(
                lambda: "default/p0" in replica.store.kube.pods,
                msg="initial replication",
            )
            # sever the follower link; write in the dark; it heals via
            # a replay (primary's resync counter moves)
            _wait(
                lambda: replica._follow_sock is not None, msg="follow sock"
            )
            replica._follow_sock.close()
            for i in range(1, 6):
                a.put_pod(Pod(name=f"p{i}", requests=Resources(cpu=1)))
            _wait(
                lambda: len(replica.store.kube.pods) == 6,
                timeout=10.0,
                msg="replica re-convergence",
            )
            with primary.store.lock, replica.store.lock:
                assert replica.store.rvs == primary.store.rvs
            assert (
                primary.registry.counter(
                    "karpenter_store_resync_total", {"kind": "replay"}
                )
                >= 1
            )
        finally:
            if a is not None:
                a.close()
            replica.stop()
            primary.stop()
