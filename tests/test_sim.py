"""Cluster-simulator suite (karpenter_tpu/sim/): determinism (same seed
twice -> byte-identical trace), trace record/replay round-trips, the
invariant checker's teeth, scenario coverage, the CLI entry, and the
satellite pieces that ride the sim PR (time-to-schedule histogram,
RemoteKubeStore on the injected clock)."""

import json
import os

import pytest

from karpenter_tpu.sim.invariants import (
    InvariantChecker,
    is_voluntary_disruption,
)
from karpenter_tpu.sim.report import build_report, percentile, wall_profile
from karpenter_tpu.sim.runner import (
    SCENARIOS,
    Scenario,
    ScenarioRunner,
    replay,
    run_scenario,
)
from karpenter_tpu.sim.trace import TraceWriter, read_tape, read_trace
from karpenter_tpu.sim.workload import Script, SimEvent, Steady, poisson


# --------------------------------------------------------------- determinism
@pytest.mark.sim
def test_same_seed_twice_is_byte_identical():
    """The determinism contract: two runs of the same scenario/seed/ticks
    produce byte-identical traces and equal reports."""
    w1 = TraceWriter()
    _, r1 = run_scenario("steady", seed=3, ticks=40, trace=w1)
    w2 = TraceWriter()
    _, r2 = run_scenario("steady", seed=3, ticks=40, trace=w2)
    assert w1.text() == w2.text()
    assert w1.sha256() == w2.sha256()
    assert r1 == r2
    assert r1["invariants"]["violations"] == []
    # the device-observatory section is part of the byte-compared report
    # surface (r1 == r2 above proves identity even though the SECOND run
    # hit process-warm jit caches): would-compile counts per kernel,
    # transfer bytes per site, the resident footprint — counts and bytes
    # only, no wall-clock keys
    dev = r1["device"]
    assert dev["compiles"] and dev["dispatches"]
    assert sum(dev["transfer_bytes"].values()) > 0
    assert set(dev) == {
        "compiles", "dispatches", "transfer_bytes", "resident",
    }
    assert "seconds" not in json.dumps(dev)
    # different seed actually changes the run (the RNG is wired through)
    w3 = TraceWriter()
    _, r3 = run_scenario("steady", seed=4, ticks=40, trace=w3)
    assert w3.text() != w1.text()


@pytest.mark.sim
def test_replay_reproduces_trace_and_report(tmp_path):
    """A recorded trace replays with no generators in the loop and
    reproduces the identical report AND identical trace bytes."""
    path = str(tmp_path / "storm.jsonl")
    w = TraceWriter(path)
    _, original = run_scenario("interruption-storm", seed=5, ticks=60, trace=w)
    assert original["invariants"]["violations"] == []
    w2 = TraceWriter()
    _, replayed, recorded = replay(path, trace=w2)
    assert recorded == original  # the trace carries the report
    assert replayed == original
    assert w2.text() == open(path).read()


@pytest.mark.sim
def test_resident_churn_byte_identical_and_warm(tmp_path):
    """The resident-tensor acceptance scenario: steady pod churn + node
    add/remove + one mid-run catalog roll, with the device-resident delta
    path on.  The run must (a) actually exercise the warm path
    (solver.resident hits dominate), (b) take the rebuild fallback at
    least for the cold start and the catalog roll, and (c) stay
    byte-identical across run/run AND run/replay — the delta path may
    change HOW tensors are built, never what any tick decides."""
    path = str(tmp_path / "resident.jsonl")
    w1 = TraceWriter(path)
    _, r1 = run_scenario("resident-churn", seed=21, ticks=70, trace=w1)
    assert r1["invariants"]["violations"] == []
    res = r1["solver"]["resident"]
    assert res["hits"] > res["rebuilds"] >= 2, res  # cold start + roll
    assert res["delta_rows"]["ticks"] > 0
    # run/run determinism
    w2 = TraceWriter()
    _, r2 = run_scenario("resident-churn", seed=21, ticks=70, trace=w2)
    assert w1.text() == w2.text()
    assert r1 == r2
    # record/replay byte-identity (no generators in the loop)
    w3 = TraceWriter()
    _, replayed, recorded = replay(path, trace=w3)
    assert recorded == r1
    assert replayed == r1
    assert w3.text() == open(path).read()


@pytest.mark.sim
def test_trace_structure(tmp_path):
    path = str(tmp_path / "t.jsonl")
    w = TraceWriter(path)
    run_scenario("steady", seed=1, ticks=10, trace=w)
    lines = read_trace(path)
    kinds = {line["t"] for line in lines}
    assert kinds == {"meta", "tick", "ev", "api", "led", "dig", "report"}
    meta = lines[0]
    assert meta == {
        "t": "meta", "v": 1, "scenario": "steady", "seed": 1,
        "ticks": 10, "tick_s": 1.0,
    }
    # every run tick has a digest; the api stream is non-empty
    digs = [l for l in lines if l["t"] == "dig"]
    assert len(digs) >= 10 and all("sha" in d for d in digs)
    assert any(l["api"] == "CreateFleet" for l in lines if l["t"] == "api")
    # the tape round-trips the event schedule
    meta2, tape, slo = read_tape(path)
    assert meta2["scenario"] == "steady" and slo is not None
    assert sum(len(evs) for _, evs in tape.values()) == sum(
        1 for l in lines if l["t"] == "ev"
    )


# ----------------------------------------------------------------- scenarios
@pytest.mark.sim
@pytest.mark.parametrize(
    "name,ticks",
    [
        ("diurnal", 100),
        ("api-storm+catalog-roll", 100),
        ("batch-waves", 50),
        ("flash-crowd", 50),
    ],
)
def test_scenario_invariants(name, ticks):
    """Every registered scenario runs clean: no invariant violations, and
    the run actually exercised the cluster."""
    runner, report = run_scenario(name, seed=7, ticks=ticks)
    assert report["invariants"]["violations"] == []
    assert report["pods"]["created"] > 0
    assert report["nodes"]["launched"] > 0
    assert report["pending"]["final"] == 0
    assert report["cost_usd"]["total"] > 0
    assert report["time_to_schedule_s"]["p95"] >= report["time_to_schedule_s"]["p50"]


@pytest.mark.sim
@pytest.mark.slow
def test_diurnal_interruption_storm_500_ticks():
    """The long one: 500 ticks of day/night load with a capacity-reclaim
    storm at peak, partial fleet fulfillment riding along."""
    runner, report = run_scenario(
        "diurnal+interruption-storm", seed=11, ticks=500
    )
    assert report["invariants"]["violations"] == []
    assert report["events"].get("spot_interruption", 0) > 10
    assert report["pods"]["created"] > 100
    assert report["pending"]["final"] == 0


# ---------------------------------------------------------------- invariants
@pytest.mark.sim
def test_invariant_checker_catches_double_launch():
    runner, _ = run_scenario("steady", seed=2, ticks=10)
    env = runner.env
    claims = [c for c in env.kube.node_claims.values() if c.provider_id]
    assert claims, "scenario should have launched something"
    # forge a duplicate: a second claim backed by the same instance
    from karpenter_tpu.api import NodeClaim

    env.kube.node_claims["forged"] = NodeClaim(
        name="forged", pool_name="default", provider_id=claims[0].provider_id
    )
    checker = InvariantChecker(env)
    checker.check_tick(0)
    assert any(v.invariant == "no-double-launch" for v in checker.violations)


@pytest.mark.sim
def test_invariant_checker_catches_ghost_node():
    runner, _ = run_scenario("steady", seed=2, ticks=10)
    env = runner.env
    from karpenter_tpu.state.kube import Node

    env.kube.nodes["ghost"] = Node(name="ghost", provider_id="i-never-was")
    checker = InvariantChecker(env)
    checker.check_tick(0)
    assert any(
        v.invariant == "registered-eq-launched" for v in checker.violations
    )


@pytest.mark.sim
def test_invariant_checker_catches_schedule_deadline():
    runner, _ = run_scenario("steady", seed=2, ticks=5)
    env = runner.env
    from karpenter_tpu.api import Pod, Resources

    pod = Pod(name="stuck", requests=Resources(cpu=1, memory="1Gi"))
    env.kube.put_pod(pod)
    checker = InvariantChecker(env, deadline_s=100.0)
    checker.note_pod(pod.key())
    env.clock.step(101.0)
    checker.check_tick(0)
    assert any(
        v.invariant == "schedule-deadline" for v in checker.violations
    )


@pytest.mark.sim
def test_voluntary_disruption_classification():
    for reason in ("expired", "drifted/image", "emptiness",
                   "consolidation/delete", "consolidation/multi"):
        assert is_voluntary_disruption(reason)
    for reason in ("spot_interruption", "rebalance_recommendation",
                   "consolidation/rollback", "state_change"):
        assert not is_voluntary_disruption(reason)


@pytest.mark.sim
def test_budget_invariant_holds_under_tight_budgets():
    """A scenario that tightens budgets mid-run and rolls the catalog
    (drift pressure) must never disrupt past the budget — the checker
    wraps the controller's own budget arithmetic, so a violation here is
    a real controller bug."""
    _, report = run_scenario("api-storm+catalog-roll", seed=13, ticks=80)
    assert not any(
        "budgets" in v for v in report["invariants"]["violations"]
    )


# ------------------------------------------------------------------- report
@pytest.mark.sim
def test_percentile_nearest_rank():
    assert percentile([], 0.5) == 0.0
    assert percentile([3.0], 0.99) == 3.0
    xs = [float(i) for i in range(1, 101)]
    assert percentile(xs, 0.50) == 51.0  # nearest-rank on 0-indexed ranks
    assert percentile(xs, 0.95) == 95.0
    assert percentile(xs, 1.0) == 100.0


@pytest.mark.sim
def test_time_to_schedule_histogram_feeds_report():
    runner, report = run_scenario("steady", seed=9, ticks=30)
    samples = runner.env.registry.histogram(
        "karpenter_pods_time_to_schedule_seconds"
    )
    assert samples, "pods scheduled -> histogram must have samples"
    assert all(s >= 0 for s in samples)
    assert report["time_to_schedule_s"]["scheduled"] == len(samples)
    # profile section is separate and explicitly wall-clock
    prof = wall_profile(runner.env.registry)
    assert prof["wall_clock"] is True
    assert prof["solver_phases"], "solves happened -> phases recorded"


# ---------------------------------------------------------------------- CLI
@pytest.mark.sim
def test_cli_run_and_replay(tmp_path, capsys):
    from karpenter_tpu.sim.cli import main

    trace = str(tmp_path / "cli.jsonl")
    rc = main(
        ["--scenario", "steady", "--seed", "1", "--ticks", "25",
         "--trace", trace]
    )
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["scenario"] == "steady"
    assert report["invariants"]["violations"] == []
    assert os.path.exists(trace)

    replay_trace = str(tmp_path / "cli.replay.jsonl")
    rc = main(["--replay", trace, "--trace", replay_trace])
    captured = capsys.readouterr()
    assert rc == 0
    assert json.loads(captured.out) == report
    assert "matches" in captured.err
    assert open(trace).read() == open(replay_trace).read()


@pytest.mark.sim
def test_cli_list_and_unknown_scenario(capsys):
    from karpenter_tpu.sim.cli import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in ("steady", "diurnal", "interruption-storm",
                 "api-storm+catalog-roll", "diurnal+interruption-storm",
                 "chaos-soak"):
        assert name in out
    assert main(["--scenario", "nope", "--ticks", "1"]) == 64


# ------------------------------------------------------------------ workload
@pytest.mark.sim
def test_poisson_sampler_seeded():
    import random

    rng = random.Random(0)
    draws = [poisson(rng, 0.5) for _ in range(200)]
    assert all(d >= 0 for d in draws)
    assert 0.3 < sum(draws) / len(draws) < 0.8  # mean ~0.5
    assert poisson(rng, 0.0) == 0


@pytest.mark.sim
def test_unknown_event_kind_is_an_error():
    runner = ScenarioRunner(
        Scenario("x", workloads=[Steady(rate=0.0)]), seed=0, ticks=1
    )
    with pytest.raises(ValueError, match="unknown sim event kind"):
        runner.apply_event(SimEvent("frobnicate", {}))


@pytest.mark.sim
def test_custom_scenario_with_az_blackout():
    """The DSL composes: an AZ goes dark mid-run and heals; capacity
    relocates and every invariant still holds."""
    scn = Scenario(
        "az-test",
        workloads=[
            Steady(rate=0.6),
            Script({
                10: [("az_down", {"zone": "zone-a"})],
                25: [("az_up", {"zone": "zone-a"})],
            }),
        ],
    )
    runner = ScenarioRunner(scn, seed=21, ticks=50)
    runner.run()
    runner.checker.raise_on_violations()
    report = build_report(runner)
    assert report["events"]["az_down"] == 1
    assert report["pending"]["final"] == 0
    # the blackout actually bit: zone-a lost its instances at tick 10
    assert report["nodes"]["launched"] > 0


# ------------------------------------------------ satellite: remote on Clock
def test_remote_store_backoff_rides_injected_clock():
    """RemoteKubeStore's retry backoff sleeps on the injectable Clock:
    under a FakeClock the retries advance SIMULATED time (remote.py no
    longer calls time.sleep — enforced by test_lint.py's wall-clock
    rule)."""
    from karpenter_tpu.state.remote import (
        BACKOFF_S,
        RETRIES,
        RemoteKubeStore,
        StoreUnavailableError,
    )
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    t0 = clock.now()
    store = RemoteKubeStore(
        "127.0.0.1", 1, start_watch=False, clock=clock, connect_timeout=0.2
    )
    with pytest.raises(StoreUnavailableError):
        store._rpc({"method": "stat"})
    expected = sum(BACKOFF_S * (2 ** a) for a in range(RETRIES - 1))
    assert clock.now() - t0 == pytest.approx(expected)
    store.close()


def test_remote_wait_synced_times_out_on_injected_clock():
    from karpenter_tpu.state.remote import RemoteKubeStore
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    store = RemoteKubeStore("127.0.0.1", 1, start_watch=False, clock=clock)
    t0 = clock.now()
    assert store.wait_synced(min_rv=5, timeout=1.0) is False
    assert clock.now() - t0 >= 1.0  # the poll waited on the fake clock
    store.close()
