"""RetryingCloud: classified retries, backoff on the injected clock, the
per-tick retry budget, and the per-API circuit breaker (cloud/retry.py) —
the AWS-SDK retry behavior the reference's providers get for free."""

import pytest

from karpenter_tpu.api import Settings
from karpenter_tpu.cloud.fake.backend import (
    CloudAPIError,
    FakeCloud,
    LaunchTemplateNotFoundError,
    MachineShape,
)
from karpenter_tpu.cloud.retry import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    THROTTLE,
    TRANSIENT,
    TERMINAL,
    CircuitOpenError,
    RetryingCloud,
    classify,
)
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.utils.clock import FakeClock


def _make(settings=None):
    clock = FakeClock()
    cloud = FakeCloud(
        clock,
        shapes=[MachineShape(name="std1.large", cpu=4, memory=16 * 2**30)],
        zones=["zone-a"],
    ).with_default_topology()
    registry = Registry()
    retrying = RetryingCloud(
        cloud,
        clock=clock,
        settings=settings
        or Settings(cluster_name="t", cloud_backoff_base=0.01,
                    cloud_backoff_max=0.1),
        registry=registry,
    )
    return clock, cloud, registry, retrying


class TestClassification:
    def test_codes(self):
        assert classify(CloudAPIError("RequestLimitExceeded")) == THROTTLE
        assert classify(CloudAPIError("Throttling")) == THROTTLE
        assert classify(CloudAPIError("InternalError")) == TRANSIENT
        assert classify(CloudAPIError("ServiceUnavailable")) == TRANSIENT
        assert classify(CloudAPIError("InsufficientInstanceCapacity")) == TERMINAL
        assert classify(LaunchTemplateNotFoundError("lt")) == TERMINAL
        assert classify(CircuitOpenError("api", 0.0)) == TERMINAL
        assert classify(ValueError("bug")) == TERMINAL

    def test_transient_error_retried_to_success(self):
        clock, cloud, registry, retrying = _make()
        t0 = clock.now()
        cloud.recorder.set_error_sequence(
            "DescribeInstances",
            [CloudAPIError("InternalError"), CloudAPIError("ServiceUnavailable")],
        )
        assert retrying.describe_instances() == []
        assert cloud.recorder.count("DescribeInstances") == 3
        # backoff was paced on the injected clock
        assert clock.now() >= t0
        assert registry.counter(
            "karpenter_cloud_api_retries_total",
            {"api": "describe_instances", "classification": TRANSIENT},
        ) == 2

    def test_throttle_retried(self):
        clock, cloud, registry, retrying = _make()
        cloud.recorder.set_next_error(
            "GetProducts", CloudAPIError("RequestLimitExceeded")
        )
        assert retrying.get_products()
        assert registry.counter(
            "karpenter_cloud_api_retries_total",
            {"api": "get_products", "classification": THROTTLE},
        ) == 1

    def test_terminal_error_passes_through_unretried(self):
        clock, cloud, registry, retrying = _make()
        cloud.recorder.set_next_error(
            "DescribeSubnets", CloudAPIError("InvalidParameterValue")
        )
        with pytest.raises(CloudAPIError, match="InvalidParameterValue"):
            retrying.describe_subnets([])
        assert cloud.recorder.count("DescribeSubnets") == 1
        assert registry.counters.get("karpenter_cloud_api_retries_total") is None

    def test_retries_exhausted_raises_last_error(self):
        clock, cloud, registry, retrying = _make()
        cloud.recorder.set_error_sequence(
            "GetParameter", [CloudAPIError("InternalError")] * 10
        )
        with pytest.raises(CloudAPIError, match="InternalError"):
            retrying.latest_image("standard", "amd64")
        # 1 initial + cloud_max_retries attempts
        assert cloud.recorder.count("GetParameter") == 1 + retrying.max_retries

    def test_ice_fleet_errors_flow_in_band_untouched(self):
        """ICE must reach the caller (and the ICE cache) unretried — both
        the in-band CreateFleet error list and nothing in the retry
        counters."""
        clock, cloud, registry, retrying = _make()
        cloud.mark_insufficient("std1.large", "zone-a", "on-demand")
        insts, errs = retrying.create_fleet(
            overrides=[{"instance_type": "std1.large", "zone": "zone-a",
                        "subnet_id": "subnet-0"}],
            capacity_type="on-demand",
        )
        assert not insts
        assert errs and errs[0].code == "InsufficientInstanceCapacity"
        assert cloud.recorder.count("CreateFleet") == 1
        assert registry.counters.get("karpenter_cloud_api_retries_total") is None

    def test_non_api_attributes_pass_through(self):
        clock, cloud, registry, retrying = _make()
        assert retrying.clock is clock
        assert retrying.recorder is cloud.recorder
        assert retrying.instances is cloud.instances
        assert retrying.zones == cloud.zones


class TestRetryBudget:
    def test_budget_exhaustion_stops_retries_until_next_tick(self):
        clock, cloud, registry, retrying = _make(
            Settings(cluster_name="t", cloud_max_retries=3,
                     cloud_retry_budget_per_tick=1,
                     cloud_backoff_base=0.01, cloud_backoff_max=0.02)
        )
        cloud.recorder.set_error_sequence(
            "DescribeInstances", [CloudAPIError("InternalError")] * 3
        )
        # first call burns the whole budget on its one allowed retry, then
        # gives up even though max_retries would allow more
        with pytest.raises(CloudAPIError):
            retrying.describe_instances()
        assert cloud.recorder.count("DescribeInstances") == 2
        # a fresh tick re-arms the budget: one error left, one retry allowed
        retrying.begin_tick()
        assert retrying.describe_instances() == []
        assert cloud.recorder.count("DescribeInstances") == 4

    def test_zero_budget_means_no_retries(self):
        clock, cloud, registry, retrying = _make(
            Settings(cluster_name="t", cloud_retry_budget_per_tick=0,
                     cloud_backoff_base=0.01, cloud_backoff_max=0.02)
        )
        cloud.recorder.set_next_error(
            "DescribeInstances", CloudAPIError("InternalError")
        )
        with pytest.raises(CloudAPIError):
            retrying.describe_instances()
        assert cloud.recorder.count("DescribeInstances") == 1


class TestStaleGuard:
    def test_gauge_tracks_max_age_across_degraded_keys(self):
        """One key recovering must not hide another key's ongoing
        degradation: the staleness gauge is the max age over every key
        currently served stale."""
        from karpenter_tpu.providers.stale import STALENESS_METRIC, StaleGuard

        clock, reg = FakeClock(), Registry()
        g = StaleGuard("subnet", clock, reg)
        g.fetch("a", lambda: 1)
        g.fetch("b", lambda: 2)
        clock.step(100.0)

        def boom():
            raise CloudAPIError("ServiceUnavailable")

        assert g.fetch("a", boom) == (1, False)
        assert reg.gauge(STALENESS_METRIC, {"provider": "subnet"}) == 100.0
        g.fetch("b", lambda: 3)  # b fresh while a still degraded
        assert reg.gauge(STALENESS_METRIC, {"provider": "subnet"}) == 100.0
        g.fetch("a", lambda: 4)  # a recovers -> fully fresh
        assert reg.gauge(STALENESS_METRIC, {"provider": "subnet"}) == 0.0
        # a key with no last-good value still raises
        with pytest.raises(CloudAPIError):
            g.fetch("never-seen", boom)


class TestCircuitBreaker:
    def _settings(self):
        return Settings(
            cluster_name="t", cloud_max_retries=0,
            cloud_circuit_failure_threshold=3,
            cloud_circuit_reset_timeout=30.0,
            cloud_backoff_base=0.01, cloud_backoff_max=0.02,
        )

    def test_opens_after_consecutive_failures_and_fails_fast(self):
        clock, cloud, registry, retrying = _make(self._settings())
        cloud.recorder.set_error_sequence(
            "DescribeSubnets", [CloudAPIError("InternalError")] * 3
        )
        for _ in range(3):
            with pytest.raises(CloudAPIError):
                retrying.describe_subnets([])
        assert retrying.circuit_state("describe_subnets") == OPEN
        assert registry.gauge(
            "karpenter_cloud_api_circuit_state", {"api": "describe_subnets"}
        ) == OPEN
        # while open: fail fast, backend untouched
        n = cloud.recorder.count("DescribeSubnets")
        with pytest.raises(CircuitOpenError):
            retrying.describe_subnets([])
        assert cloud.recorder.count("DescribeSubnets") == n

    def test_half_open_probe_success_closes(self):
        clock, cloud, registry, retrying = _make(self._settings())
        cloud.recorder.set_error_sequence(
            "DescribeSubnets", [CloudAPIError("InternalError")] * 3
        )
        for _ in range(3):
            with pytest.raises(CloudAPIError):
                retrying.describe_subnets([])
        clock.step(31.0)  # past the reset timeout -> half-open probe allowed
        assert retrying.describe_subnets([]) == []
        assert retrying.circuit_state("describe_subnets") == CLOSED
        assert registry.gauge(
            "karpenter_cloud_api_circuit_state", {"api": "describe_subnets"}
        ) == CLOSED

    def test_half_open_probe_failure_reopens(self):
        clock, cloud, registry, retrying = _make(self._settings())
        cloud.recorder.set_error_sequence(
            "DescribeSubnets", [CloudAPIError("InternalError")] * 4
        )
        for _ in range(3):
            with pytest.raises(CloudAPIError):
                retrying.describe_subnets([])
        clock.step(31.0)
        with pytest.raises(CloudAPIError):  # the probe fails -> re-open
            retrying.describe_subnets([])
        assert retrying.circuit_state("describe_subnets") == OPEN

    def test_terminal_errors_do_not_trip_the_breaker(self):
        clock, cloud, registry, retrying = _make(self._settings())
        for _ in range(5):
            cloud.recorder.set_next_error(
                "DescribeSubnets", CloudAPIError("InvalidParameterValue")
            )
            with pytest.raises(CloudAPIError):
                retrying.describe_subnets([])
        assert retrying.circuit_state("describe_subnets") == CLOSED

    def test_breakers_are_per_api(self):
        clock, cloud, registry, retrying = _make(self._settings())
        cloud.recorder.set_error_sequence(
            "DescribeSubnets", [CloudAPIError("InternalError")] * 3
        )
        for _ in range(3):
            with pytest.raises(CloudAPIError):
                retrying.describe_subnets([])
        assert retrying.circuit_state("describe_subnets") == OPEN
        # a different API is unaffected
        assert retrying.describe_instances() == []
        assert retrying.circuit_state("describe_instances") == CLOSED
