"""Device-resident consolidation SEARCH: parity, twin actions, determinism.

The contract under test (docs/designs/consolidation-search.md): the
population path — removal masks scored through
`TensorScheduler.evaluate_population`, one vmapped dispatch per round,
with counts / removed slots / FFD class order derived ON DEVICE from the
mask — must be VERDICT-identical to the sequential per-subset simulation
(`DisruptionController._simulate`), and the search's proposal/selection
schedule must be a pure function of (seed, universe, verdicts), so the
two scoring backends take identical actions tick for tick.  The only
acceptable difference between the backends is speed.
"""

import random

import numpy as np
import pytest

from karpenter_tpu.api import Disruption, Pod, Resources
from karpenter_tpu.cloud.fake.backend import generate_catalog
from karpenter_tpu.controllers.disruption import _RemovalEvaluator
from karpenter_tpu.scheduling.popsearch import SearchPlan
from karpenter_tpu.testing import Environment

SIZES = [
    Resources(cpu=0.5, memory="1Gi"),
    Resources(cpu=1, memory="2Gi"),
    Resources(cpu=2, memory="4Gi"),
]


def _build_env(seed: int, npods: int, cpus=(4, 8)) -> Environment:
    from karpenter_tpu.api.objects import reset_name_sequences

    reset_name_sequences()
    env = Environment(shapes=generate_catalog(generations=(1, 2), cpus=cpus))
    env.default_node_class()
    env.default_node_pool(
        disruption=Disruption(consolidation_policy="WhenUnderutilized")
    )
    rng = random.Random(seed)
    for _ in range(npods):
        env.kube.put_pod(Pod(requests=rng.choice(SIZES)))
    env.settle(max_rounds=60)
    assert not env.kube.pending_pods()
    return env


def _ranked_candidates(dc):
    dc._budgets = dc._remaining_budgets()
    return sorted(
        (c for c in dc._candidates() if dc._consolidatable(c)),
        key=lambda c: c.disruption_cost(),
    )


def _fuzz_keys(rng: random.Random, n: int):
    """The mask shapes the plan proposes: singletons, prefixes,
    drop-ones, plus seeded random subsets of every size."""
    keys = [(i,) for i in range(n)]
    keys += [tuple(range(k)) for k in range(2, n + 1)]
    full = tuple(range(n))
    keys += [full[:i] + full[i + 1 :] for i in range(n)]
    keys += [
        tuple(sorted(rng.sample(range(n), rng.randint(2, n))))
        for _ in range(40)
    ]
    return list(dict.fromkeys(k for k in keys if k))


def _assert_population_parity(env, keys):
    """Every population verdict the kernel answers must bit-equal the
    sequential `_simulate` for the same subset; `needs_host` elements are
    exempt by construction — the controller runs exactly the sequential
    path for them."""
    dc = env.operator.disruption
    cands = _ranked_candidates(dc)
    inv = dc._pool_inventory()
    ev = _RemovalEvaluator(dc, cands, inv)
    ev._sync_scheduler()
    masks = np.zeros((len(keys), len(ev._universe)), bool)
    for r, key in enumerate(keys):
        masks[r, list(key)] = True
    verdicts = dc._scheduler.evaluate_population(masks, ev._universe)
    answered = 0
    for key, v in zip(keys, verdicts):
        subset = [cands[i] for i in key]
        if v.needs_host:
            continue
        fits, price, _vn = dc._simulate(subset, inv)
        answered += 1
        assert v.fits == fits, (key, v, (fits, price))
        assert v.replacement_price == pytest.approx(price, abs=1e-9), (
            key, v, (fits, price),
        )
    return answered, len(keys)


@pytest.mark.parametrize("seed,npods", [(0, 120), (3, 90)])
def test_population_parity_seeded_cluster(seed, npods):
    env = _build_env(seed, npods, cpus=(4, 8) if seed == 0 else (8, 16))
    cands = _ranked_candidates(env.operator.disruption)
    assert len(cands) >= 3
    rng = random.Random(seed + 100)
    keys = _fuzz_keys(rng, min(len(cands), 10))
    answered, total = _assert_population_parity(env, keys)
    # the kernel must answer the bulk of the population, or the search
    # is a sequential walk in disguise
    assert answered >= total * 0.6, (answered, total)


@pytest.mark.sim
def test_population_parity_storm_snapshot():
    """A mid-run snapshot of the consolidation-storm scenario: the
    population verdicts match the sequential simulations on the cluster
    states the storm actually produces (post-scale-down troughs), not
    just on synthetic fixtures."""
    from karpenter_tpu.sim.runner import SCENARIOS, ScenarioRunner

    scn = SCENARIOS["consolidation-storm"](48)
    runner = ScenarioRunner(scn, seed=5, ticks=48)
    for t in range(30):
        events = [
            ev
            for w in scn.workloads
            for ev in w.events(t, runner.rng, runner.view)
        ]
        runner._tick(t, scn.tick_s, "run", events)
    env = runner.env
    cands = _ranked_candidates(env.operator.disruption)
    if len(cands) < 3:
        pytest.skip("storm snapshot produced too few candidates")
    keys = _fuzz_keys(random.Random(9), min(len(cands), 10))
    _assert_population_parity(env, keys)


def test_forced_fallback_twin_actions():
    """Flipping the batched scorer off must not change ANY consolidation
    decision: the search plan proposes identical masks either way (its
    RNG sees only the seed, the universe, and bit-identical verdicts),
    so two identically-seeded clusters — one scored on device, one
    through the sequential `_simulate` — take the same actions tick for
    tick, multi-node population winners included."""
    digests = []
    for batched in (True, False):
        env = _build_env(5, 110)
        dc = env.operator.disruption
        dc.use_batched_consolidation = batched
        # small search so the sequential twin stays fast; BOTH twins use
        # the same shape (the knobs size the plan, not the backend)
        dc.search_rounds = 2
        dc.search_population = 16
        rng = random.Random(99)
        keys = sorted(env.kube.pods.keys())
        # a mass scale-down strands several nodes at once, so multi-node
        # population winners actually fire inside the twin window
        for key in rng.sample(keys, (keys and len(keys) * 3 // 5) or 0):
            env.kube.delete_pod(key)
        states = []
        for _ in range(12):
            env.clock.step(65)
            env.step(2.0)
            states.append(
                (
                    tuple(sorted(
                        name
                        for name, cl in env.kube.node_claims.items()
                        if cl.deleted_at is not None
                    )),
                    tuple(sorted(dc._pending)),
                    tuple(sorted(
                        (p.key(), p.node_name or "")
                        for p in env.kube.pods.values()
                    )),
                )
            )
        digests.append(states)
        # the multi-node population search must actually have concluded
        # passes (not just the single scan)
        winners = env.registry.counters.get(
            "karpenter_consolidation_search_winners_total", {}
        )
        assert sum(winners.values()) > 0, winners
        if batched:
            evals = env.registry.counters.get(
                "karpenter_consolidation_evals_total", {}
            )
            by_path = {k[0][1]: v for k, v in evals.items() if k}
            assert by_path.get("batched", 0) > 0, by_path
        assert (
            env.registry.counter(
                "karpenter_consolidation_verdict_mismatch_total"
            )
            == 0
        )
    assert digests[0] == digests[1]


def test_search_plan_is_seed_deterministic():
    """Two plans with equal (seed, universe) fed equal verdicts propose
    identical mask sequences and pick the identical winner — the
    twin-run guarantee's host half, isolated."""
    def drive(seed):
        rng = random.Random(7)
        plan = SearchPlan(
            n=9,
            prices=[0.1 * (i + 1) for i in range(9)],
            spot=[False] * 9,
            population=24,
            rounds=3,
            seed=seed,
        )
        proposed = []
        while True:
            keys = plan.propose()
            if not keys:
                break
            proposed.append(keys)
            # a deterministic fake scorer: feasibility by parity of the
            # subset sum, price a fixed fraction of the subset's total
            results = []
            for key in keys:
                fits = (sum(key) % 3) != 0
                price = 0.0 if len(key) % 2 else 0.04 * len(key)
                results.append((fits, price))
            plan.observe(keys, results)
        return proposed, plan.best()

    p1, b1 = drive(42)
    p2, b2 = drive(42)
    assert p1 == p2 and b1 == b2
    p3, b3 = drive(43)
    assert p3 != p1  # the seed actually steers the proposals

    # structured seeds always ride: singletons, prefixes, drop-ones, full
    first = set(p1[0])
    assert all((i,) in first for i in range(9))
    assert tuple(range(9)) in first
    assert tuple(range(4)) in first


def test_search_plan_acceptability_rules():
    """The plan's action predicate mirrors the controller's: spot
    members make a priced replacement unacceptable, and replacements
    must be STRICTLY cheaper than the members they retire."""
    plan = SearchPlan(
        n=3, prices=[0.4, 0.5, 0.6], spot=[False, True, False],
        population=8, rounds=1, seed=1,
    )
    assert plan.acceptable((0, 2), True, 0.0)
    assert plan.acceptable((0, 2), True, 0.9)  # 0.9 < 1.0: strictly cheaper
    assert not plan.acceptable((0, 2), True, 1.0)  # not strictly cheaper
    assert not plan.acceptable((0, 1), True, 0.3)  # spot member: delete-only
    assert plan.acceptable((0, 1), True, 0.0)  # pure delete is fine
    assert not plan.acceptable((0,), True, 0.0)  # singles are the scan's job
    assert not plan.acceptable((0, 2), False, 0.0)


@pytest.mark.sim
def test_consolidation_storm_byte_identical(tmp_path):
    """The consolidation-search acceptance scenario: mass scale-downs +
    diurnal trough + spot interruptions drive the population search hard.
    The run must (a) actually take multi-node population actions,
    (b) keep verdict mismatches at 0, (c) populate the report's
    consolidation.search section, and (d) stay byte-identical across
    run/run AND run/replay — the seeded search may change HOW subsets
    are found, never what a replay decides."""
    from karpenter_tpu.sim.runner import run_scenario, replay
    from karpenter_tpu.sim.trace import TraceWriter

    path = str(tmp_path / "storm.jsonl")
    w1 = TraceWriter(path)
    runner, r1 = run_scenario(
        "consolidation-storm", seed=7, ticks=60, trace=w1
    )
    assert r1["invariants"]["violations"] == []
    assert (
        runner.env.registry.counter(
            "karpenter_consolidation_verdict_mismatch_total"
        )
        == 0
    )
    search = r1["consolidation"]["search"]
    assert search["passes"] > 0
    assert search["rounds_max"] >= 1
    assert search["population_max"] >= 2
    assert sum(search["winners"].values()) == search["passes"]
    # the storm must actually produce multi-node wins, or it isn't
    # driving the search at all
    acted = sum(
        v for k, v in search["winners"].items() if k in ("delete", "replace")
    )
    assert acted > 0, search["winners"]
    assert (
        r1["cluster_events"]["disruptions_by_reason"].get(
            "consolidation/multi", 0
        )
        > 0
    )
    # run/run determinism (the seeded search rides the pass counter, so
    # a fresh process proposes the identical mask schedule)
    w2 = TraceWriter()
    _, r2 = run_scenario("consolidation-storm", seed=7, ticks=60, trace=w2)
    assert w1.text() == w2.text()
    assert r1 == r2
    # record/replay byte-identity (no generators in the loop)
    w3 = TraceWriter()
    _, replayed, recorded = replay(path, trace=w3)
    assert recorded == r1
    assert replayed == r1
    assert w3.text() == open(path).read()


def test_constraint_shapes_route_to_descent():
    """Constraint shapes the mask encoding cannot replay (here: a volume
    claim) must send the whole pass to the legacy descent UP FRONT — a
    host-decidable choice shared by both verdict backends — rather than
    proposing a population the base would refuse and grinding every mask
    through the sequential fallback."""
    env = _build_env(2, 60)
    dc = env.operator.disruption
    cands = _ranked_candidates(dc)
    assert len(cands) >= 2
    cands[0].reschedulable[0].volume_claims = ["pvc-x"]
    ev = _RemovalEvaluator(dc, cands, dc._pool_inventory())
    before = len(
        env.registry.histogram("karpenter_consolidation_search_rounds")
    )
    dc._consolidate_multi(cands, ev)
    # the legacy descent ran: no NEW population-search pass was recorded
    # (earlier samples came from the build/settle reconciles)
    assert (
        len(env.registry.histogram("karpenter_consolidation_search_rounds"))
        == before
    )


def test_search_settings_wire_through():
    """Settings.consolidation_search_rounds / consolidation_population_
    size reach the controller (and validate)."""
    from karpenter_tpu.api import Settings

    s = Settings(
        cluster_name="t",
        consolidation_search_rounds=3,
        consolidation_population_size=64,
    )
    s.validate()
    env = Environment(settings=s)
    dc = env.operator.disruption
    assert dc.search_rounds == 3
    assert dc.search_population == 64
    with pytest.raises(ValueError):
        Settings(cluster_name="t", consolidation_search_rounds=0).validate()
    with pytest.raises(ValueError):
        Settings(
            cluster_name="t", consolidation_population_size=1
        ).validate()
