"""Consistency checker (SURVEY §2b core-controller list): cross-object
invariant violations surface as events + karpenter_consistency_errors."""

import pytest

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.controllers.consistency import CHECK_PERIOD
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    return Environment()


def _provision(env, n=2):
    env.default_node_class()
    env.default_node_pool()
    pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(n)]
    for p in pods:
        env.kube.put_pod(p)
    env.settle()
    assert not env.kube.pending_pods()
    return pods


def _run_checker(env):
    env.clock.step(CHECK_PERIOD + 1)
    env.operator.consistency.reconcile()


def _violations(env, check):
    return [
        e
        for e in env.kube.events
        if e[1] == "ConsistencyViolation" and e[3].startswith(check)
    ]


class TestConsistency:
    def test_healthy_cluster_is_quiet(self, env):
        _provision(env)
        _run_checker(env)
        assert not _violations(env, "")
        assert (
            env.registry.counter(
                "karpenter_consistency_errors", {"check": "claim-instance"}
            )
            == 0
        )

    def test_claim_without_instance(self, env):
        _provision(env)
        claim = next(iter(env.kube.node_claims.values()))
        # instance vanishes behind karpenter's back (no event, no GC yet)
        del env.cloud.instances[claim.provider_id]
        _run_checker(env)
        assert _violations(env, "claim-instance")
        assert env.registry.counter(
            "karpenter_consistency_errors", {"check": "claim-instance"}
        ) >= 1

    def test_node_without_claim(self, env):
        _provision(env)
        from karpenter_tpu.state.kube import Node

        env.kube.put_node(
            Node(
                name="rogue",
                provider_id="i-rogue",
                labels={},
                taints=[],
                allocatable=Resources(cpu=4),
                ready=True,
            )
        )
        _run_checker(env)
        assert _violations(env, "node-claim")

    def test_capacity_lie(self, env):
        _provision(env)
        claim = next(iter(env.kube.node_claims.values()))
        node = env.kube.node_by_provider_id(claim.provider_id)
        node.allocatable = node.allocatable + Resources(cpu=1000)
        _run_checker(env)
        assert _violations(env, "capacity")

    def test_pod_bound_to_missing_node(self, env):
        pods = _provision(env)
        pod = env.kube.pods[pods[0].key()]
        pod.node_name = "never-existed"
        _run_checker(env)
        assert _violations(env, "pod-binding")

    def test_stale_nomination(self, env):
        _provision(env)
        ghost = Pod(requests=Resources(cpu=1))
        env.kube.put_pod(ghost)
        # nominate AFTER stepping past the check period: nominations now
        # expire (state/cluster.py NOMINATION_TTL), so an old one would
        # self-heal before the checker ever saw it — the checker's job is
        # the window where a LIVE nomination points at a missing node
        env.clock.step(CHECK_PERIOD + 1)
        env.cluster.nominate(ghost.key(), "missing-node")
        env.operator.consistency.reconcile()
        assert _violations(env, "nomination")

    def test_nomination_expires(self, env):
        """A nomination the kubelet never converts to a bind ages out so
        the pod returns to the provisionable pool (the deadlock guard the
        chaos suite relies on)."""
        from karpenter_tpu.state.cluster import NOMINATION_TTL

        _provision(env)
        ghost = Pod(requests=Resources(cpu=1))
        env.kube.put_pod(ghost)
        env.cluster.nominate(ghost.key(), "some-node")
        assert env.cluster.nominated_node(ghost.key()) == "some-node"
        env.clock.step(NOMINATION_TTL + 1)
        assert env.cluster.nominated_node(ghost.key()) is None

    def test_rate_limited(self, env):
        _provision(env)
        env.operator.consistency.reconcile()
        before = env.cloud.recorder.count("DescribeInstances")
        env.operator.consistency.reconcile()  # within CHECK_PERIOD: no-op
        assert env.cloud.recorder.count("DescribeInstances") == before
