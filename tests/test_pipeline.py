"""Pipelined reconcile: twin actions, speculation lifecycle, the seam.

The contract under test (docs/designs/pipelined-reconcile.md): the
pipelined schedule — disruption's consolidation search dispatched at
tick boundaries so its device rounds run under the other controllers'
host phases — must take IDENTICAL actions tick for tick to the strict
sequential schedule, the way PR 9 proved the population search against
the sequential descent.  The fingerprint guard is what makes that true:
a speculation is adopted only when the authoritative pass reads exactly
the state the speculation read, and discarded wholesale otherwise.  The
only acceptable difference between the schedules is latency.
"""

import random
import threading

import pytest

from karpenter_tpu.api import Disruption, Pod, Resources, Settings
from karpenter_tpu.api.objects import reset_name_sequences
from karpenter_tpu.batcher.core import CoalesceWindow
from karpenter_tpu.cloud.fake.backend import generate_catalog
from karpenter_tpu.controllers.disruption import _RemovalEvaluator
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.pipeline import StageSpec, TickPipeline, run_concurrently
from karpenter_tpu.scheduling.popsearch import SearchPlan
from karpenter_tpu.testing import Environment
from karpenter_tpu.utils.trace import Tracer

SIZES = [
    Resources(cpu=0.5, memory="1Gi"),
    Resources(cpu=1, memory="2Gi"),
    Resources(cpu=2, memory="4Gi"),
]


def _build_env(
    seed: int, npods: int, pipelined: bool = True
) -> Environment:
    reset_name_sequences()
    env = Environment(
        shapes=generate_catalog(generations=(1, 2), cpus=(4, 8)),
        settings=Settings(enable_pipelined_reconcile=pipelined),
    )
    env.default_node_class()
    env.default_node_pool(
        disruption=Disruption(consolidation_policy="WhenUnderutilized")
    )
    rng = random.Random(seed)
    for _ in range(npods):
        env.kube.put_pod(Pod(requests=rng.choice(SIZES)))
    env.settle(max_rounds=60)
    assert not env.kube.pending_pods()
    return env


def _spec_counts(env) -> dict:
    got = env.registry.counters.get(
        "karpenter_pipeline_speculation_total", {}
    )
    return {dict(k)["outcome"]: v for k, v in got.items()}


# ------------------------------------------------------------- the seam
class _Recorder:
    def __init__(self, log, name):
        self.log = log
        self.name = name

    def reconcile(self):
        self.log.append(("mutate", self.name))


def _specs(log):
    a = _Recorder(log, "a")
    b = _Recorder(log, "b")
    return [
        StageSpec("a", a),
        StageSpec(
            "b", b,
            dispatch=lambda: log.append(("dispatch", "b")),
            advance=lambda: log.append(("advance", "b")),
        ),
    ]


def test_sequential_mode_is_the_plain_mutate_order():
    """enabled=False runs ONLY the mutate stages, in declaration order —
    the bit-for-bit sequential schedule the simulator byte-compares."""
    log = []
    pipe = TickPipeline(_specs(log), Registry(), Tracer(), enabled=False)
    assert pipe.run(lambda n, c: c.reconcile(), lambda: True)
    assert log == [("mutate", "a"), ("mutate", "b")]


def test_pipelined_mode_brackets_with_advance_and_dispatch():
    log = []
    pipe = TickPipeline(_specs(log), Registry(), Tracer(), enabled=True)
    assert pipe.run(lambda n, c: c.reconcile(), lambda: True)
    assert log == [
        ("advance", "b"),
        ("mutate", "a"),
        ("mutate", "b"),
        ("dispatch", "b"),
    ]


def test_gate_aborts_between_stages():
    """A False gate (mid-tick leadership loss) stops the tick before the
    next stage — mutate or speculative — and reports the abort."""
    log = []
    calls = {"n": 0}

    def gate():
        calls["n"] += 1
        return calls["n"] <= 2  # advance + first mutate run, then stop

    pipe = TickPipeline(_specs(log), Registry(), Tracer(), enabled=True)
    assert not pipe.run(lambda n, c: c.reconcile(), gate)
    assert log == [("advance", "b"), ("mutate", "a")]


def test_backoff_skips_speculative_stages_only():
    """ready(name)=False (the operator's crash-requeue backoff) skips a
    controller's dispatch/advance — speculating for a consumer that
    will not run is pure waste — while the mutate stage keeps its own
    backoff handling."""
    log = []
    pipe = TickPipeline(_specs(log), Registry(), Tracer(), enabled=True)
    assert pipe.run(
        lambda n, c: c.reconcile(), lambda: True,
        ready=lambda name: name != "b",
    )
    assert log == [("mutate", "a"), ("mutate", "b")]


def test_legacy_batch_window_settings_still_ingest():
    """The pre-rename names keep working across an image upgrade: a
    configmap or environment carrying batch_idle_duration /
    batch_max_duration loads into the provision_batch_* fields (new
    name wins when both are present)."""
    import json

    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json") as f:
        json.dump(
            {"cluster_name": "c", "batch_idle_duration": 2.5,
             "batch_max_duration": 20.0},
            f,
        )
        f.flush()
        s = Settings.from_file(f.name)
    assert s.provision_batch_idle_s == 2.5
    assert s.provision_batch_max_s == 20.0
    s = Settings.from_env(
        {"KARPENTER_BATCH_IDLE_DURATION": "3.0",
         "KARPENTER_PROVISION_BATCH_MAX_S": "30.0"}
    )
    assert s.provision_batch_idle_s == 3.0
    assert s.provision_batch_max_s == 30.0
    # the new name wins when both are present
    s = Settings.from_env(
        {"KARPENTER_BATCH_IDLE_DURATION": "3.0",
         "KARPENTER_PROVISION_BATCH_IDLE_S": "4.0"}
    )
    assert s.provision_batch_idle_s == 4.0


def test_speculative_stage_crash_is_contained():
    """A raising dispatch/advance hook is counted and logged; the tick's
    mutate stages still run — a speculation bug may cost latency, never
    actions."""
    log = []
    reg = Registry()
    specs = [
        StageSpec(
            "a", _Recorder(log, "a"),
            dispatch=lambda: 1 / 0,
            advance=lambda: 1 / 0,
        ),
    ]
    pipe = TickPipeline(specs, reg, Tracer(), enabled=True)
    assert pipe.run(lambda n, c: c.reconcile(), lambda: True)
    assert log == [("mutate", "a")]
    assert reg.counter(
        "karpenter_pipeline_stage_errors_total",
        {"controller": "a", "stage": "dispatch"},
    ) == 1
    assert reg.counter(
        "karpenter_pipeline_stage_errors_total",
        {"controller": "a", "stage": "advance"},
    ) == 1


def test_run_concurrently_serial_is_in_order():
    """max_workers<=1 runs on the calling thread in submission order —
    the simulator's determinism knob."""
    order = []
    main = threading.get_ident()

    def mk(i):
        def fn():
            assert threading.get_ident() == main
            order.append(i)
            if i == 1:
                raise RuntimeError("boom")
        return fn

    outcomes = run_concurrently([mk(0), mk(1), mk(2)], max_workers=1)
    assert order == [0, 1, 2]
    assert outcomes[0] is None and outcomes[2] is None
    assert isinstance(outcomes[1], RuntimeError)


def test_run_concurrently_pool_preserves_result_order():
    import time

    def mk(i):
        def fn():
            time.sleep(0.01 * (3 - i))
            if i == 0:
                raise ValueError("first")
        return fn

    outcomes = run_concurrently([mk(0), mk(1), mk(2)], max_workers=3)
    assert isinstance(outcomes[0], ValueError)
    assert outcomes[1] is None and outcomes[2] is None


# ----------------------------------------------------- batching window
def test_coalesce_window_idle_and_max():
    w = CoalesceWindow(idle_s=1.0, max_s=10.0)
    assert not w.open and not w.ready(0.0)
    w.observe(0.0)
    assert w.open and not w.ready(0.5)
    w.observe(0.9)  # fresh arrival pushes the idle deadline
    assert not w.ready(1.5)
    assert w.ready(1.9)
    # max wins over a steady trickle
    w.reset()
    w.observe(0.0)
    for t in range(1, 12):
        w.observe(t * 0.9)
    assert w.ready(10.0)
    # non-fresh re-observation does not push the deadline
    w.reset()
    w.observe(0.0)
    w.observe(0.9, fresh=False)
    assert w.ready(1.0)


def test_pod_batcher_window_semantics():
    """The provisioner's pod window on the shared CoalesceWindow: seen
    pods re-observed next tick do not push the idle deadline; fresh pods
    do; max closes a steady trickle."""
    from karpenter_tpu.controllers.provisioning import PodBatcher
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    b = PodBatcher(clock, idle_s=1.0, max_s=10.0)
    pods = [Pod(name="p0"), Pod(name="p1")]
    b.observe(pods[:1])
    clock.step(0.5)
    b.observe(pods[:1])  # same pod again: not an arrival
    assert not b.ready()
    clock.step(0.6)
    assert b.ready()  # idle elapsed from the FIRST observation
    b.reset()
    b.observe(pods[:1])
    clock.step(0.8)
    b.observe(pods)  # fresh pod pushes the idle deadline
    clock.step(0.5)
    assert not b.ready()
    clock.step(0.6)
    assert b.ready()


# ------------------------------------------------------ the twin proof
def test_pipelined_vs_sequential_twin_actions():
    """Flipping the pipelined schedule on must not change ANY decision:
    two identically-seeded clusters — one on the strict sequential
    order, one dispatching/advancing speculative search rounds at tick
    boundaries — take the same actions tick for tick, and the pipelined
    twin actually adopts speculations along the way (it is not
    trivially identical because nothing ever overlapped)."""
    digests = []
    for pipelined in (False, True):
        env = _build_env(7, 110, pipelined=pipelined)
        op = env.operator
        dc = op.disruption
        dc.search_rounds = 2
        dc.search_population = 16
        rng = random.Random(99)
        keys = sorted(env.kube.pods.keys())
        for key in rng.sample(keys, len(keys) * 3 // 5):
            env.kube.delete_pod(key)
        states = []
        for _ in range(12):
            env.clock.step(65)
            env.step(2.0)
            states.append(
                (
                    tuple(sorted(
                        name
                        for name, cl in env.kube.node_claims.items()
                        if cl.deleted_at is not None
                    )),
                    tuple(sorted(dc._pending)),
                    tuple(sorted(
                        (p.key(), p.node_name or "")
                        for p in env.kube.pods.values()
                    )),
                )
            )
        digests.append(states)
        counts = _spec_counts(env)
        if pipelined:
            assert counts.get("adopted", 0) > 0, counts
        else:
            assert not counts, counts
        assert (
            env.registry.counter(
                "karpenter_consolidation_verdict_mismatch_total"
            )
            == 0
        )
    assert digests[0] == digests[1]


def test_quiet_cluster_adopts_every_tick():
    """Steady state — full nodes, nothing to consolidate, nothing
    pending: the speculation dispatched at each tick's tail is adopted
    at the next tick's slot, and the overlap histogram records the
    device-concurrent host time."""
    env = _build_env(3, 30)
    op = env.operator
    assert op.pipeline.enabled  # the production default
    for _ in range(10):
        env.clock.step(10)
        env.step(1.0)
    counts = _spec_counts(env)
    assert counts.get("adopted", 0) >= 8, counts
    hists = env.registry.histograms.get(
        "karpenter_reconcile_overlap_seconds", {}
    )
    assert sum(h.count for h in hists.values()) == counts["adopted"]


def test_state_change_discards_speculation():
    """Any relevant mutation between dispatch and join — here a pod
    landing on a candidate node — flips the fingerprint and the pass
    recomputes synchronously; the discarded speculation is counted
    stale and no verdict from it survives (mismatch counter stays 0)."""
    env = _build_env(4, 40)
    op = env.operator
    # tick once so a speculation is in flight
    env.clock.step(10)
    env.step(1.0)
    assert op.disruption._speculation is not None
    # mutate state the search reads: delete a bound pod outright
    bound = [p for p in env.kube.pods.values() if p.node_name]
    env.kube.delete_pod(bound[0].key())
    env.clock.step(10)
    env.step(1.0)
    counts = _spec_counts(env)
    assert counts.get("stale", 0) >= 1, counts
    assert (
        env.registry.counter(
            "karpenter_consolidation_verdict_mismatch_total"
        )
        == 0
    )


def test_sequential_mode_never_speculates():
    env = _build_env(5, 30, pipelined=False)
    for _ in range(5):
        env.clock.step(10)
        env.step(1.0)
    assert not _spec_counts(env)
    assert env.operator.disruption._speculation is None


def test_sim_runner_forces_pipeline_off():
    """The simulator's byte-compared traces record the sequential
    schedule even when the scenario's settings ask for pipelining."""
    from karpenter_tpu.sim.runner import SCENARIOS, ScenarioRunner

    scn = SCENARIOS["steady"](4)
    scn.settings = {
        **scn.settings, "enable_pipelined_reconcile": True,
    }
    runner = ScenarioRunner(scn, seed=1, ticks=4)
    assert runner.env.operator.pipeline.enabled is False


@pytest.mark.sim
def test_diurnal_interruption_storm_byte_identical(tmp_path):
    """The pipelined-reconcile acceptance scenario's determinism half:
    diurnal+interruption-storm stays byte-identical run/run AND
    run/replay (the sequential schedule the sim enforces; the twin test
    above extends the guarantee to the pipelined schedule)."""
    from karpenter_tpu.sim.runner import replay, run_scenario
    from karpenter_tpu.sim.trace import TraceWriter

    path = str(tmp_path / "storm.jsonl")
    w1 = TraceWriter(path)
    _, r1 = run_scenario(
        "diurnal+interruption-storm", seed=11, ticks=40, trace=w1
    )
    assert r1["invariants"]["violations"] == []
    w2 = TraceWriter()
    _, r2 = run_scenario(
        "diurnal+interruption-storm", seed=11, ticks=40, trace=w2
    )
    assert w2.text() == open(path).read()
    assert r2 == r1
    w3 = TraceWriter()
    _, replayed, recorded = replay(path, trace=w3)
    assert recorded == r1
    assert replayed == r1
    assert w3.text() == open(path).read()


# -------------------------------------------- annealing warm start
def test_search_plan_admits_warm_masks():
    """Warm keys ride round 0 after the structured seeds; out-of-range
    keys are dropped defensively."""
    plan = SearchPlan(
        n=4, prices=[1.0] * 4, spot=[False] * 4,
        population=64, rounds=1, seed=1,
        warm=[(1, 3), (0, 9), (2,)],  # (0,9) out of range, (2,) too small
    )
    keys = plan.propose()
    assert (1, 3) in keys
    assert plan.warm == [(1, 3)]


def test_warm_start_parity_and_fingerprint_gate():
    """The cross-pass warm start satellite: a warm-started pass proposes
    the previous pass's survivors, and its winner is acceptable by
    exactly the same rules as the cold pass's — while a changed
    universe fingerprint drops the warm store entirely."""
    env = _build_env(6, 90)
    dc = env.operator.disruption
    dc.search_rounds = 2
    dc.search_population = 16
    # strand capacity so the search has real candidates
    rng = random.Random(17)
    keys = sorted(env.kube.pods.keys())
    for key in rng.sample(keys, len(keys) // 2):
        env.kube.delete_pod(key)
    dc._budgets = dc._remaining_budgets()
    cands = sorted(
        (c for c in dc._candidates() if dc._consolidatable(c)),
        key=lambda c: c.disruption_cost(),
    )
    assert len(cands) >= 2
    inv = dc._pool_inventory()
    dc._warm_store = None
    dc._search_seq = 0
    cold = dc._search_multi(cands, _RemovalEvaluator(dc, cands, inv))
    assert dc._warm_store is not None
    ufp, survivors = dc._warm_store
    assert ufp == dc._universe_fingerprint(cands)
    # unchanged universe: the warm masks are exactly the survivors
    assert dc._warm_masks(cands) == survivors
    warm = dc._search_multi(cands, _RemovalEvaluator(dc, cands, inv))
    if survivors:
        assert all(k in warm.seen for k in survivors)
    # both winners (when either exists) satisfy the SAME acceptability
    # predicate — warm seeding changes coverage, never the rules
    for plan in (cold, warm):
        best = plan.best()
        if best is not None:
            fits, price = plan.results[best.indices]
            assert plan.acceptable(best.indices, fits, price)
            assert cold.acceptable(best.indices, fits, price)
    # a changed universe fingerprint yields no warm masks
    dc._warm_store = (("bogus",), survivors)
    assert dc._warm_masks(cands) == []


# ------------------------------------------------- launch concurrency
def test_launch_max_concurrency_setting_validated():
    s = Settings(launch_max_concurrency=0)
    with pytest.raises(ValueError, match="launch_max_concurrency"):
        s.validate()
    s = Settings(provision_batch_idle_s=5.0, provision_batch_max_s=1.0)
    with pytest.raises(ValueError, match="provision_batch_max_s"):
        s.validate()


def test_launch_inflight_gauge_visible_during_flush():
    """The karpenter_launch_inflight gauge reads the flush size WHILE
    creates are in flight and returns to 0 after — a stuck CreateFleet
    is visible while it is stuck."""
    env = Environment()
    env.default_node_class()
    env.default_node_pool()
    seen = []
    op = env.operator
    orig = op.cloud_provider.create

    def create(claim):
        seen.append(
            env.registry.gauge("karpenter_launch_inflight")
        )
        return orig(claim)

    op.cloud_provider.create = create
    for i in range(3):
        env.kube.put_pod(Pod(requests=Resources(cpu=2, memory="4Gi")))
    op.provisioner.reconcile()  # opens the batch window
    env.clock.step(2)
    op.provisioner.reconcile()  # window closed: solve + launch
    assert seen, "no launches happened"
    assert all(v and v >= 1 for v in seen), seen
    assert env.registry.gauge("karpenter_launch_inflight") == 0.0
