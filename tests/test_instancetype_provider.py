"""InstanceType provider behaviors (reference pkg/providers/instancetype)."""

import pytest

from karpenter_tpu.api import labels as L
from karpenter_tpu.providers.instancetype import kube_reserved_cpu, kube_reserved_memory
from karpenter_tpu.testing import Environment


@pytest.fixture
def env():
    return Environment()


def test_list_builds_full_catalog(env):
    types = env.instance_types.list()
    assert len(types) >= 180
    t = next(it for it in types if it.name == "std1.xlarge")
    assert t.capacity.cpu == 8
    # VM memory overhead applied (types.go:196-206)
    assert t.capacity.memory == pytest.approx(32 * 2**30 * (1 - 0.075))
    # offerings: every zone x {od, spot}
    assert len(t.offerings) == 6
    spot = [o for o in t.offerings if o.capacity_type == "spot"]
    od = [o for o in t.offerings if o.capacity_type == "on-demand"]
    assert all(s.price < o.price for s in spot for o in od)


def test_kube_reserved_math():
    # 6% of first core, 1% of 2nd, 0.5% of 3-4, 0.25% beyond (types.go:343-362)
    assert kube_reserved_cpu(1) == pytest.approx(0.06)
    assert kube_reserved_cpu(2) == pytest.approx(0.07)
    assert kube_reserved_cpu(4) == pytest.approx(0.08)
    assert kube_reserved_cpu(8) == pytest.approx(0.08 + 4 * 0.0025)
    assert kube_reserved_memory(110) == (11 * 110 + 255) * 2**20


def test_allocatable_less_than_capacity(env):
    t = env.instance_types.list()[0]
    alloc = t.allocatable()
    assert alloc.cpu < t.capacity.cpu
    assert alloc.memory < t.capacity.memory


def test_ice_cache_masks_offering_and_seqnum_invalidates(env):
    before = env.instance_types.list()
    t0 = next(it for it in before if it.name == "std1.xlarge")
    assert all(o.available for o in t0.offerings)
    env.unavailable.mark_unavailable("spot", "std1.xlarge", "zone-a")
    after = env.instance_types.list()  # seqnum bump -> cache miss
    t1 = next(it for it in after if it.name == "std1.xlarge")
    masked = [o for o in t1.offerings if not o.available]
    assert len(masked) == 1
    assert masked[0].zone == "zone-a" and masked[0].capacity_type == "spot"


def test_cache_hit_avoids_cloud_calls(env):
    env.instance_types.list()
    n = env.cloud.recorder.count("DescribeInstanceTypes")
    env.instance_types.list()
    assert env.cloud.recorder.count("DescribeInstanceTypes") == n


def test_requirements_labels(env):
    t = next(it for it in env.instance_types.list() if it.name == "gpu2.xlarge")
    assert t.requirements.get(L.LABEL_INSTANCE_CATEGORY).has("accelerated")
    assert t.requirements.get(L.LABEL_INSTANCE_GPU_COUNT) is not None
    assert t.capacity.get(L.RESOURCE_GPU) >= 1


def test_kubelet_max_pods_override(env):
    pool = env.default_node_pool(kubelet_max_pods=42)
    types = env.instance_types.list(pool=pool)
    assert all(t.capacity.get(L.RESOURCE_PODS) == 42 for t in types)


class TestKubeletReservedOverrides:
    def test_kube_reserved_override_replaces_per_key(self, env):
        """kubeletConfiguration kubeReserved/systemReserved/evictionHard
        override the computed defaults per resource key (reference
        types.go:326-399)."""
        from karpenter_tpu.api import Resources

        nc = env.default_node_class()
        pool = env.default_node_pool(
            name="tuned",
            kubelet_kube_reserved=Resources(cpu=1),
            kubelet_system_reserved=Resources(memory="256Mi"),
            kubelet_eviction_hard=Resources(memory="512Mi"),
        )
        default_pool = env.default_node_pool(name="plain")
        tuned = env.instance_types.list(pool, nc)
        plain = env.instance_types.list(default_pool, nc)
        t, p = tuned[0], plain[0]
        assert t.name == p.name
        # cpu reserve replaced; memory reserve kept from the curve
        assert t.overhead.kube_reserved.get("cpu") == 1.0
        assert t.overhead.kube_reserved.get("memory") == p.overhead.kube_reserved.get("memory")
        assert t.overhead.system_reserved.get("memory") == 256 * 2**20
        assert t.overhead.eviction_threshold.get("memory") == 512 * 2**20
        # allocatable shrinks accordingly
        assert t.allocatable().get("cpu") < p.allocatable().get("cpu")

    def test_pods_per_core_caps_density(self, env):
        """podsPerCore scales pod capacity with vCPUs, capped by maxPods
        (reference pod-density.md:43)."""
        nc = env.default_node_class()
        pool = env.default_node_pool(name="dense", kubelet_pods_per_core=2)
        types = env.instance_types.list(pool, nc)
        from karpenter_tpu.api import labels as L

        for t in types:
            cpu = t.capacity.get("cpu")
            assert t.capacity.get(L.RESOURCE_PODS) <= max(2 * cpu, 1)
        small = min(types, key=lambda t: t.capacity.get("cpu"))
        assert small.capacity.get(L.RESOURCE_PODS) == 2 * small.capacity.get("cpu")
