"""The diagnosis layer (docs/designs/observability.md §5): SLO burn-rate
engine, streaming anomaly detection, tick flight recorder, and the
``doctor`` CLI — plus their operator wiring and the simulator's
scenario-declared rules with byte-identical breach/recovery replay."""

import json
import os

import pytest

from karpenter_tpu.api import Pod, Resources, Settings
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.obs.context import set_tick
from karpenter_tpu.obs.detect import AnomalyDetector, robust_baseline
from karpenter_tpu.obs.doctor import diagnose, render_diagnosis
from karpenter_tpu.obs.events import EventLedger
from karpenter_tpu.obs.flight import FlightRecorder, load_flight, read_flight
from karpenter_tpu.obs.slo import (
    BURN_CAP,
    SIGNALS,
    SLOEngine,
    SLORule,
    default_rules,
)
from karpenter_tpu.testing import Environment
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_tick():
    set_tick("")
    yield
    set_tick("")


def _engine(rules, clock=None):
    clock = clock or FakeClock()
    reg = Registry()
    reg.ledger = EventLedger(clock=clock, registry=reg)
    return SLOEngine(reg, clock, rules=rules), reg, clock


# ------------------------------------------------------------- SLO engine
class TestSLOEngine:
    RULE = SLORule(
        name="pending", signal="pending_pod_age_max", threshold=10.0,
        budget=0.5, fast_window_s=10.0, slow_window_s=20.0,
    )

    def test_breach_needs_both_windows_then_recovers(self):
        eng, reg, clock = _engine([self.RULE])
        set_tick("tick-000001")
        # healthy history first: the slow window must confirm, so a
        # single violating tick cannot page
        for _ in range(20):
            reg.set("karpenter_pods_pending_age_seconds", 1.0)
            clock.step(1.0)
            assert eng.evaluate() == []
        reg.set("karpenter_pods_pending_age_seconds", 99.0)
        clock.step(1.0)
        assert eng.evaluate() == []  # fast window pages, slow not yet
        assert reg.gauge(
            "karpenter_slo_burn_rate", {"rule": "pending", "window": "fast"}
        ) > 0.0
        breached_at = None
        for i in range(20):
            clock.step(1.0)
            if eng.evaluate() == ["pending"]:
                breached_at = i
                break
        assert breached_at is not None
        assert reg.gauge("karpenter_slo_status", {"rule": "pending"}) == 1.0
        assert reg.counter(
            "karpenter_slo_breaches_total", {"rule": "pending"}
        ) == 1
        (ev,) = [
            e for e in reg.ledger.recent() if e.type == "SLOBreach"
        ]
        assert ev.attrs["rule"] == "pending"
        assert ev.attrs["signal"] == "pending_pod_age_max"
        assert ev.trace_id == "tick-000001"
        assert float(ev.attrs["burn_fast"]) >= 1.0
        assert float(ev.attrs["burn_slow"]) >= 1.0
        # recovery: the fast window drains below burn 1
        reg.set("karpenter_pods_pending_age_seconds", 0.0)
        recovered = False
        for _ in range(15):
            clock.step(1.0)
            eng.evaluate()
            if reg.gauge("karpenter_slo_status", {"rule": "pending"}) == 0.0:
                recovered = True
                break
        assert recovered
        (rec,) = [
            e for e in reg.ledger.recent() if e.type == "SLORecovered"
        ]
        assert rec.attrs["rule"] == "pending"
        assert float(rec.attrs["breached_s"]) > 0.0
        rep = eng.report()["rules"]["pending"]
        assert rep["breaches"] == 1 and rep["recoveries"] == 1
        assert rep["status"] == "ok" and rep["breached_s"] > 0.0

    def test_zero_budget_rule_pages_immediately(self):
        rule = SLORule(
            name="mismatch", signal="verdict_mismatches", threshold=0.0,
            budget=0.0, fast_window_s=10.0, slow_window_s=20.0,
        )
        eng, reg, clock = _engine([rule])
        assert eng.evaluate() == []  # 0 mismatches: healthy
        reg.inc("karpenter_consolidation_verdict_mismatch_total")
        clock.step(1.0)
        assert eng.evaluate() == ["mismatch"]
        assert reg.gauge(
            "karpenter_slo_burn_rate", {"rule": "mismatch", "window": "fast"}
        ) == BURN_CAP

    def test_signal_without_data_is_not_judged(self):
        rule = SLORule(
            name="hit-rate", signal="compile_cache_hit_rate",
            threshold=0.5, op="<", budget=0.1,
        )
        eng, reg, clock = _engine([rule])
        for _ in range(5):
            clock.step(1.0)
            assert eng.evaluate() == []
        # fewer than 20 observations: the signal stays None, no gauges
        assert reg.gauge("karpenter_slo_status", {"rule": "hit-rate"}) is None

    def test_floor_rule_uses_less_than(self):
        rule = SLORule(
            name="hit-rate", signal="compile_cache_hit_rate",
            threshold=0.5, op="<", budget=0.0,
            fast_window_s=10.0, slow_window_s=10.0,
        )
        eng, reg, clock = _engine([rule])
        reg.inc(
            "karpenter_solver_compile_cache_misses_total",
            {"consumer": "provisioner"}, by=30,
        )
        clock.step(1.0)
        assert eng.evaluate() == ["hit-rate"]  # 0% hit rate < 50% floor

    def test_disabled_rule_never_evaluates(self):
        rule = SLORule(
            name="pending", signal="pending_pod_age_max", threshold=-1.0,
            budget=0.0, enabled=False,
        )
        eng, reg, clock = _engine([rule])
        reg.set("karpenter_pods_pending_age_seconds", 5.0)
        assert eng.evaluate() == []
        assert reg.gauge("karpenter_slo_status", {"rule": "pending"}) is None


class TestDefaultRules:
    def test_defaults_cover_the_issue_signal_set(self):
        rules = {r.name: r for r in default_rules()}
        assert {
            "tick-duration-p99", "pending-pod-age", "verdict-mismatch",
            "cloud-circuit-open", "compile-cache-hit-rate",
            "provider-staleness",
        } <= set(rules)
        for r in rules.values():
            assert r.signal in SIGNALS

    def test_settings_overrides_merge_and_extend(self):
        s = Settings(
            cluster_name="t",
            slo_rules={
                "pending-pod-age": {"threshold": 30.0, "budget": 0.2},
                "tick-duration-p99": {"enabled": False},
                "my-staleness": {
                    "signal": "provider_staleness_max", "threshold": 5.0,
                },
            },
        )
        rules = {r.name: r for r in default_rules(s)}
        assert rules["pending-pod-age"].threshold == 30.0
        assert rules["pending-pod-age"].budget == 0.2
        assert not rules["tick-duration-p99"].enabled
        assert rules["my-staleness"].signal == "provider_staleness_max"

    def test_unknown_signal_and_missing_signal_rejected(self):
        with pytest.raises(ValueError, match="unknown signal"):
            default_rules(
                Settings(cluster_name="t", slo_rules={
                    "x": {"signal": "nope", "threshold": 1.0},
                })
            )
        with pytest.raises(ValueError, match="must name a signal"):
            default_rules(
                Settings(cluster_name="t", slo_rules={
                    "x": {"threshold": 1.0},
                })
            )


# ------------------------------------------------------ anomaly detection
class TestAnomalyDetector:
    def _detector(self, **kw):
        clock = FakeClock()
        reg = Registry()
        reg.ledger = EventLedger(clock=clock, registry=reg)
        det = AnomalyDetector(reg, clock, **kw)
        return det, reg, clock

    def test_spike_detected_with_attribution(self):
        det, reg, clock = self._detector()
        for _ in range(16):
            reg.observe(
                "karpenter_solver_phase_seconds", 0.004, {"phase": "compile"}
            )
        assert det.scan() == []  # a flat baseline is healthy
        reg.observe(
            "karpenter_solver_phase_seconds", 0.2, {"phase": "compile"}
        )
        (det_ev,) = det.scan()
        assert det_ev["series"] == "karpenter_solver_phase_seconds"
        assert det_ev["phase"] == "compile"
        assert det_ev["baseline_s"] == pytest.approx(0.004)
        assert det_ev["observed_s"] == pytest.approx(0.2)
        assert det_ev["magnitude"] == pytest.approx(50.0)
        (ev,) = [
            e for e in reg.ledger.recent() if e.type == "AnomalyDetected"
        ]
        assert ev.attrs["phase"] == "compile"
        assert reg.counter(
            "karpenter_anomaly_detected_total",
            {"series": "karpenter_solver_phase_seconds", "phase": "compile"},
        ) == 1

    def test_cold_series_and_micro_jitter_stay_quiet(self):
        det, reg, clock = self._detector()
        # below min_baseline: unjudgeable
        for _ in range(4):
            reg.observe(
                "karpenter_solver_phase_seconds", 0.001, {"phase": "pad"}
            )
        reg.observe("karpenter_solver_phase_seconds", 0.5, {"phase": "pad"})
        assert det.scan() == []
        # microsecond wiggle never pages even at huge relative magnitude
        for _ in range(16):
            reg.observe(
                "karpenter_solver_phase_seconds", 1e-5, {"phase": "decode"}
            )
        reg.observe(
            "karpenter_solver_phase_seconds", 31e-5, {"phase": "decode"}
        )
        assert det.scan() == []  # 31x the baseline, but under min_abs_s

    def test_cooldown_suppresses_repeats_until_clock_advances(self):
        det, reg, clock = self._detector(cooldown_s=60.0)
        for _ in range(16):
            reg.observe(
                "karpenter_solver_phase_seconds", 0.004, {"phase": "compile"}
            )
        det.scan()
        reg.observe(
            "karpenter_solver_phase_seconds", 0.2, {"phase": "compile"}
        )
        assert len(det.scan()) == 1
        reg.observe(
            "karpenter_solver_phase_seconds", 0.3, {"phase": "compile"}
        )
        assert det.scan() == []  # within cooldown
        clock.step(61.0)
        reg.observe(
            "karpenter_solver_phase_seconds", 2.0, {"phase": "compile"}
        )
        assert len(det.scan()) == 1

    def test_disabled_detector_is_inert(self):
        det, reg, clock = self._detector(enabled=False)
        for _ in range(16):
            reg.observe(
                "karpenter_solver_phase_seconds", 0.004, {"phase": "compile"}
            )
        reg.observe(
            "karpenter_solver_phase_seconds", 5.0, {"phase": "compile"}
        )
        assert det.scan() == []

    def test_robust_baseline_mad_floor(self):
        med, scale = robust_baseline([0.01] * 9)
        assert med == 0.01 and scale == pytest.approx(0.001)  # 10% floor


# -------------------------------------------------------- flight recorder
class TestFlightRecorder:
    def _recorder(self, capacity=4):
        clock = FakeClock()
        reg = Registry()
        led = EventLedger(clock=clock, registry=reg)
        reg.ledger = led
        return FlightRecorder(clock, reg, ledger=led, capacity=capacity), \
            reg, led, clock

    def test_ring_bounded_and_slices_captured(self):
        fr, reg, led, clock = self._recorder(capacity=4)
        for i in range(6):
            clock.step(1.0)
            led.emit("PodNominated", pod=f"p-{i}")
            reg.inc("karpenter_nodeclaims_launched", {"nodepool": "default"})
            reg.observe(
                "karpenter_solver_phase_seconds", 0.01 * (i + 1),
                {"phase": "compile"},
            )
            fr.record(i + 1, f"tick-{i + 1:06d}", 0.005, {"pending": i})
        lines = fr.dump_lines("manual")
        flight = read_flight("\n".join(lines))
        assert flight["meta"]["trigger"] == "manual"
        assert flight["meta"]["ticks"] == 4  # bounded: last 4 of 6
        ticks = flight["ticks"]
        assert [t["seq"] for t in ticks] == [3, 4, 5, 6]
        # each tick carries exactly its own ledger slice...
        assert [t["events"][0]["attrs"]["pod"] for t in ticks] == [
            "p-2", "p-3", "p-4", "p-5",
        ]
        # ...its counter deltas...
        assert all(
            t["counters"]['karpenter_nodeclaims_launched{nodepool=default}']
            == 1.0
            for t in ticks
        )
        # ...and per-phase histogram deltas summing to that tick's time
        h = ticks[-1]["hists"][
            "karpenter_solver_phase_seconds{phase=compile}"
        ]
        assert h["count"] == 1 and h["sum_s"] == pytest.approx(0.06)

    def test_dump_writes_jsonl_and_counts_trigger(self, tmp_path):
        fr, reg, led, clock = self._recorder()
        fr.record(1, "tick-000001", 0.01, {"pending": 0})
        path = tmp_path / "flight.jsonl"
        fr.dump(str(path), trigger="slo_breach")
        assert reg.counter(
            "karpenter_flight_dumps_total", {"trigger": "slo_breach"}
        ) == 1
        flight = load_flight(str(path))
        assert flight["meta"]["trigger"] == "slo_breach"
        assert len(flight["ticks"]) == 1

    def test_dump_renders_through_obs_cli(self, tmp_path, capsys):
        """Acceptance: a flight dump renders through the existing
        `python -m karpenter_tpu obs` path into valid Chrome-trace JSON."""
        from karpenter_tpu.__main__ import main as cli_main

        fr, reg, led, clock = self._recorder()
        for i in range(3):
            clock.step(1.0)
            led.emit("CircuitOpen", api="create_fleet", failures=4)
            fr.record(i + 1, f"tick-{i + 1:06d}", 0.004, {"pending": 2,
                                                          "nodes": 1})
        path = tmp_path / "flight-tick-000003-slo_breach.jsonl"
        fr.dump(str(path), trigger="slo_breach")
        out = tmp_path / "flight.chrome.json"
        rc = cli_main(["obs", str(path), "--out", str(out)])
        assert rc == 0
        chrome = json.loads(out.read_text())
        events = chrome["traceEvents"]
        assert events
        phases = {e["ph"] for e in events}
        assert {"X", "i", "M", "C"} <= phases
        ticks = [e for e in events if e["ph"] == "X"]
        assert any(e["name"] == "tick 3" for e in ticks)
        instants = [e for e in events if e["ph"] == "i"]
        assert all(e["name"] == "CircuitOpen" for e in instants)
        captured = capsys.readouterr()
        assert "cluster events recorded in the flight dump" in captured.out
        assert "CircuitOpen" in captured.out


# ------------------------------------------------------------------ doctor
def _synthetic_regression_dump(tmp_path):
    """A seeded synthetic regression: 16 warm ticks, then a catalog roll
    forces a compile-cache miss storm — compile-phase time per tick blows
    up 40x while every other phase holds steady."""
    clock = FakeClock()
    reg = Registry()
    led = EventLedger(clock=clock, registry=reg)
    reg.ledger = led
    fr = FlightRecorder(clock, reg, ledger=led, capacity=64)
    for i in range(24):
        clock.step(1.0)
        set_tick(f"tick-{i + 1:06d}")
        warm = i < 16
        if i == 16:
            reg.event("CatalogRolled", provider="image")
        reg.observe(
            "karpenter_solver_phase_seconds",
            0.002 if warm else 0.08, {"phase": "compile"},
        )
        reg.observe(
            "karpenter_solver_phase_seconds", 0.004, {"phase": "dispatch"}
        )
        reg.inc(
            "karpenter_solver_compile_cache_hits_total"
            if warm else "karpenter_solver_compile_cache_misses_total",
            {"consumer": "provisioner"},
        )
        fr.record(i + 1, f"tick-{i + 1:06d}", 0.01, {"pending": 0})
    path = tmp_path / "flight-regression.jsonl"
    fr.dump(str(path), trigger="manual")
    return path


class TestDoctor:
    def test_names_regressing_phase_and_cites_trigger(self, tmp_path):
        """Acceptance: on the synthetic catalog-roll regression, doctor
        names the regressing phase AND cites the triggering event in its
        suspected-cause output."""
        path = _synthetic_regression_dump(tmp_path)
        diag = diagnose(load_flight(str(path)))
        assert "solver/compile" in diag["regressing_phases"]
        assert "solver/dispatch" not in diag["regressing_phases"]
        causes = diag["suspected_causes"]
        assert causes, diag
        roll_cause = causes[0]
        assert "CatalogRolled" in roll_cause
        assert "compile-cache misses spiked" in roll_cause
        assert "solver/compile" in roll_cause
        assert "8 misses after vs 0 before" in roll_cause
        # the terminal rendering carries the same story
        text = render_diagnosis(diag)
        assert "REGRESSING" in text
        assert "suspected causes:" in text
        assert "CatalogRolled" in text

    def test_circuit_open_preceding_stall_is_a_cause(self, tmp_path):
        clock = FakeClock()
        reg = Registry()
        led = EventLedger(clock=clock, registry=reg)
        reg.ledger = led
        fr = FlightRecorder(clock, reg, ledger=led, capacity=64)
        for i in range(12):
            clock.step(1.0)
            if i == 4:
                led.emit("CircuitOpen", api="create_fleet", failures=5)
            pending = 0 if i < 4 else 3 * (i - 3)  # stall after the open
            fr.record(i + 1, f"tick-{i + 1:06d}", 0.01,
                      {"pending": pending})
        path = tmp_path / "flight-stall.jsonl"
        fr.dump(str(path), trigger="manual")
        diag = diagnose(load_flight(str(path)))
        (cause,) = [
            c for c in diag["suspected_causes"] if "CircuitOpen" in c
        ]
        assert "create_fleet" in cause
        assert "provisioning stall" in cause

    def test_bench_verdict_folds_into_causes(self, tmp_path):
        path = _synthetic_regression_dump(tmp_path)
        verdict = {
            "ok": False,
            "regressed": ["schedule_10k_pods_500_types_p50"],
            "lines": [],
        }
        diag = diagnose(load_flight(str(path)), bench_verdict=verdict)
        assert any(
            "schedule_10k_pods_500_types_p50" in c
            for c in diag["suspected_causes"]
        )

    def test_dump_without_device_sections_has_no_device_causes(
        self, tmp_path
    ):
        """Backward compatibility: a pre-observatory dump (no `device`
        sections) diagnoses exactly as before — zero device causes, and
        the device summary stays all-zero."""
        path = _synthetic_regression_dump(tmp_path)
        diag = diagnose(load_flight(str(path)))
        assert not any(
            "device" in c or "transfer regression" in c
            for c in diag["suspected_causes"]
        )
        assert diag["device"] == {
            "compiles": 0, "warm_recompiles": 0, "transfer_bytes": 0,
            "resident_bytes_final": 0,
        }


def _device_dump(
    tmp_path,
    name: str,
    roll: bool = False,
    transfer_spike: bool = False,
    warm_no_roll: bool = False,
    recompile_event: bool = False,
):
    """Forge a flight dump with device sections: 24 ticks, quiet through
    tick 15, then the configured pathology from tick 16 on.  Resident
    delta rows stay FLAT throughout — the transfer spike is never
    justified by the cluster delta."""
    clock = FakeClock()
    reg = Registry()
    led = EventLedger(clock=clock, registry=reg)
    reg.ledger = led
    fr = FlightRecorder(clock, reg, ledger=led, capacity=64)
    for i in range(24):
        clock.step(1.0)
        set_tick(f"tick-{i + 1:06d}")
        device = {
            "compiles": 0, "warm_recompiles": 0, "dispatches": 2,
            "transfer_bytes": 2048, "resident_bytes": 500_000,
            "resident_delta_bytes": 0,
        }
        if roll and i == 16:
            reg.event("CatalogRolled", provider="image")
        if roll and i >= 16:
            device.update(compiles=3, warm_recompiles=3)
        if transfer_spike and i >= 16:
            device["transfer_bytes"] = 300_000
        if warm_no_roll and i == 10:
            device.update(compiles=1, warm_recompiles=1)
        if recompile_event and i == 12:
            reg.event(
                "DeviceRecompile", fn="pack_kernel", compile_s=0.82
            )
        # the per-tick delta rows the transfer rule normalizes by
        reg.observe("karpenter_solver_resident_delta_rows", 4.0)
        fr.record(
            i + 1, f"tick-{i + 1:06d}", 0.01, {"pending": 0},
            device=device,
        )
    path = tmp_path / f"flight-{name}.jsonl"
    fr.dump(str(path), trigger="manual")
    return path


class TestDoctorDeviceRules:
    def test_recompile_storm_after_catalog_roll_is_named(self, tmp_path):
        """Acceptance: doctor names the recompile storm from the dump
        alone — compile activity concentrated after CatalogRolled, with
        the triggering event cited and the warm count called out."""
        path = _device_dump(tmp_path, "storm", roll=True)
        diag = diagnose(load_flight(str(path)))
        (cause,) = [
            c for c in diag["suspected_causes"]
            if "device recompile storm" in c
        ]
        assert "CatalogRolled" in cause
        assert "24 device compile(s)" in cause  # 8 ticks x 3
        assert "vs 0 before" in cause
        assert "warm jit entry points" in cause
        assert diag["device"]["compiles"] == 24
        assert diag["device"]["warm_recompiles"] == 24
        text = render_diagnosis(diag)
        assert "device:" in text and "recompile storm" in text

    def test_transfer_spike_without_delta_rows_is_named(self, tmp_path):
        """Acceptance: a warm tick uploading more than its delta rows
        justify is a suspected cause, from the dump alone."""
        path = _device_dump(tmp_path, "xfer", transfer_spike=True)
        diag = diagnose(load_flight(str(path)))
        (cause,) = [
            c for c in diag["suspected_causes"]
            if "transfer regression" in c
        ]
        assert "300000B" in cause and "2048B" in cause
        assert "delta rows stayed flat" in cause

    def test_warm_recompile_without_roll_is_flagged(self, tmp_path):
        path = _device_dump(tmp_path, "warm", warm_no_roll=True)
        diag = diagnose(load_flight(str(path)))
        (cause,) = [
            c for c in diag["suspected_causes"]
            if "not explained by a catalog roll" in c
        ]
        assert "warm-tick device recompile" in cause
        assert "tick 10" in cause

    def test_roll_without_compile_spike_does_not_silence_warm_rule(
        self, tmp_path
    ):
        """A catalog roll that explains NOTHING (no compile activity
        after it) must not swallow earlier warm recompiles — the warm
        rule is independent of the storm rule, not its else-arm."""
        clock = FakeClock()
        reg = Registry()
        led = EventLedger(clock=clock, registry=reg)
        reg.ledger = led
        fr = FlightRecorder(clock, reg, ledger=led, capacity=64)
        for i in range(24):
            clock.step(1.0)
            set_tick(f"tick-{i + 1:06d}")
            device = {
                "compiles": 0, "warm_recompiles": 0, "dispatches": 2,
                "transfer_bytes": 2048, "resident_bytes": 500_000,
                "resident_delta_bytes": 0,
            }
            if i == 5:  # warm recompile long BEFORE the roll
                device.update(compiles=1, warm_recompiles=1)
            if i == 20:  # a roll with no compile spike behind it
                reg.event("CatalogRolled", provider="image")
            fr.record(
                i + 1, f"tick-{i + 1:06d}", 0.01, {"pending": 0},
                device=device,
            )
        path = tmp_path / "flight-roll-no-spike.jsonl"
        fr.dump(str(path), trigger="manual")
        diag = diagnose(load_flight(str(path)))
        assert any(
            "not explained by a catalog roll" in c
            for c in diag["suspected_causes"]
        ), diag["suspected_causes"]
        assert not any(
            "recompile storm" in c for c in diag["suspected_causes"]
        )

    def test_device_recompile_event_restated_as_cause(self, tmp_path):
        path = _device_dump(tmp_path, "evt", recompile_event=True)
        diag = diagnose(load_flight(str(path)))
        (cause,) = [
            c for c in diag["suspected_causes"]
            if "warm recompile of device fn" in c
        ]
        assert "pack_kernel" in cause and "0.82" in cause

    def test_quiet_device_sections_raise_no_causes(self, tmp_path):
        path = _device_dump(tmp_path, "quiet")
        diag = diagnose(load_flight(str(path)))
        assert not any(
            "recompile" in c or "transfer regression" in c
            for c in diag["suspected_causes"]
        ), diag["suspected_causes"]

    def test_cli_on_dump_and_live_endpoint(self, tmp_path, capsys):
        from karpenter_tpu.__main__ import main as cli_main
        from karpenter_tpu.obs.http import start_telemetry

        path = _synthetic_regression_dump(tmp_path)
        rc = cli_main(["doctor", str(path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "suspected causes:" in out and "CatalogRolled" in out
        # live mode: fetch the same ring from /debug/flight
        clock = FakeClock()
        reg = Registry()
        led = EventLedger(clock=clock, registry=reg)
        reg.ledger = led
        fr = FlightRecorder(clock, reg, ledger=led, capacity=8)
        fr.record(1, "tick-000001", 0.01, {"pending": 0})
        server = start_telemetry(0, reg, ledger=led, flight=fr,
                                 host="127.0.0.1")
        try:
            port = server.server_address[1]
            rc = cli_main(["doctor", f"http://127.0.0.1:{port}"])
            assert rc == 0
            assert "flight dump: 1 tick(s)" in capsys.readouterr().out
        finally:
            server.shutdown()

    def test_cli_rejects_garbage(self, tmp_path, capsys):
        from karpenter_tpu.__main__ import main as cli_main

        bad = tmp_path / "not-a-flight.json"
        bad.write_text('{"hello": 1}\n')
        rc = cli_main(["doctor", str(bad)])
        assert rc == 64
        assert "doctor:" in capsys.readouterr().err


def _tenant_wait_dump(tmp_path, name: str, starved: bool):
    """Forge a service flight dump: four tenants solving every tick.
    When ``starved``, tenant 'd' waits ~25x the fleet median and eats
    backpressure refusals; otherwise every tenant waits the same."""
    clock = FakeClock()
    reg = Registry()
    led = EventLedger(clock=clock, registry=reg)
    reg.ledger = led
    fr = FlightRecorder(clock, reg, ledger=led, capacity=64)
    for i in range(12):
        clock.step(1.0)
        set_tick(f"tick-{i + 1:06d}")
        for tenant in ("a", "b", "c"):
            reg.observe(
                "karpenter_service_solve_wait_seconds",
                0.002, {"tenant": tenant},
            )
        reg.observe(
            "karpenter_service_solve_wait_seconds",
            0.05 if starved else 0.002, {"tenant": "d"},
        )
        if starved and i % 3 == 0:
            reg.inc(
                "karpenter_service_refusals_total",
                {"tenant": "d", "reason": "inflight-cap"},
            )
        fr.record(i + 1, f"tick-{i + 1:06d}", 0.01, {"pending": 0})
    path = tmp_path / f"flight-{name}.jsonl"
    fr.dump(str(path), trigger="manual")
    return path


class TestDoctorTenantRules:
    def test_starved_tenant_is_named_with_refusals(self, tmp_path):
        """Acceptance: one tenant's solve-wait running far past the
        fleet median is a suspected cause, from the dump alone, with
        its backpressure refusals cited."""
        path = _tenant_wait_dump(tmp_path, "starved", starved=True)
        diag = diagnose(load_flight(str(path)))
        (cause,) = [
            c for c in diag["suspected_causes"]
            if "starving in the solver service" in c
        ]
        assert "tenant 'd'" in cause
        assert "50.0ms" in cause and "2.0ms" in cause
        assert "25.0x" in cause
        assert "4 backpressure refusal(s)" in cause
        text = render_diagnosis(diag)
        assert "starving in the solver service" in text

    def test_balanced_fleet_raises_no_starvation_cause(self, tmp_path):
        path = _tenant_wait_dump(tmp_path, "balanced", starved=False)
        diag = diagnose(load_flight(str(path)))
        assert not any(
            "starving" in c for c in diag["suspected_causes"]
        ), diag["suspected_causes"]

    def test_single_tenant_dump_never_compares_to_itself(self, tmp_path):
        """A lone tenant has no fleet to starve against — even a slow
        one must not self-flag."""
        clock = FakeClock()
        reg = Registry()
        led = EventLedger(clock=clock, registry=reg)
        reg.ledger = led
        fr = FlightRecorder(clock, reg, ledger=led, capacity=64)
        for i in range(8):
            clock.step(1.0)
            set_tick(f"tick-{i + 1:06d}")
            reg.observe(
                "karpenter_service_solve_wait_seconds",
                0.5, {"tenant": "only"},
            )
            fr.record(i + 1, f"tick-{i + 1:06d}", 0.01, {"pending": 0})
        path = tmp_path / "flight-lone.jsonl"
        fr.dump(str(path), trigger="manual")
        diag = diagnose(load_flight(str(path)))
        assert not any(
            "starving" in c for c in diag["suspected_causes"]
        ), diag["suspected_causes"]


# ------------------------------------------------------- operator wiring
class TestOperatorDiagnosis:
    def test_breach_dumps_flight_to_flight_dir(self, tmp_path):
        """An always-violated zero-budget rule breaches on the first
        tick; the operator dumps the flight ring into flight_dir and the
        breach is a ledger fact on the tick's trace ID."""
        env = Environment(
            settings=Settings(
                cluster_name="test",
                flight_dir=str(tmp_path),
                slo_rules={
                    "always-fire": {
                        "signal": "pending_pod_age_max",
                        "threshold": -1.0, "budget": 0.0,
                        "fast_window_s": 5.0, "slow_window_s": 5.0,
                    },
                },
            )
        )
        env.default_node_class()
        env.default_node_pool()
        env.step(1.0)
        breaches = [
            e for e in env.operator.ledger.recent() if e.type == "SLOBreach"
        ]
        assert breaches and breaches[0].attrs["rule"] == "always-fire"
        assert breaches[0].trace_id.startswith("tick-")
        dumps = [f for f in os.listdir(tmp_path) if "slo_breach" in f]
        assert len(dumps) == 1
        assert env.registry.counter(
            "karpenter_flight_dumps_total", {"trigger": "slo_breach"}
        ) == 1
        flight = load_flight(str(tmp_path / dumps[0]))
        # the dumped ring contains the breaching tick INCLUDING its own
        # SLOBreach event (the diagnosis tail records after evaluating)
        assert any(
            ev["type"] == "SLOBreach"
            for t in flight["ticks"] for ev in t["events"]
        )
        # a persisting breach must not dump again every tick
        env.step(1.0)
        assert env.registry.counter(
            "karpenter_flight_dumps_total", {"trigger": "slo_breach"}
        ) == 1

    def test_controller_crash_dumps_flight(self, tmp_path):
        env = Environment(
            settings=Settings(
                cluster_name="test", flight_dir=str(tmp_path),
            )
        )
        env.default_node_class()
        env.default_node_pool()
        env.step(1.0)

        def boom():
            raise RuntimeError("synthetic controller crash")

        env.operator.tagging.reconcile = boom
        env.step(1.0)
        dumps = [f for f in os.listdir(tmp_path) if "controller_crash" in f]
        assert dumps
        assert env.registry.counter(
            "karpenter_flight_dumps_total", {"trigger": "controller_crash"}
        ) == 1.0

    def test_tick_duration_histogram_and_flight_ring_grow(self):
        env = Environment()
        env.default_node_class()
        env.default_node_pool()
        for _ in range(3):
            env.step(1.0)
        h = env.registry.histograms[
            "karpenter_reconcile_tick_duration_seconds"
        ][()]
        assert h.count == 3
        assert all(v > 0 for v in h.samples)
        assert len(env.operator.flight._ring) == 3


# ------------------------------------ simulator: byte-identical breaches
@pytest.mark.sim
class TestSimSLO:
    def test_chaos_breach_and_recovery_replay_byte_identical(self, tmp_path):
        """Acceptance: a seeded sim scenario with injected chaos trips an
        SLO burn-rate breach and a later recovery, both ledger events
        with the tick's trace ID, and the run is byte-identical across
        --replay (led lines + `slo` report section included)."""
        from karpenter_tpu.sim.runner import replay, run_scenario
        from karpenter_tpu.sim.trace import TraceWriter, read_trace

        p1, p2 = tmp_path / "t1.jsonl", tmp_path / "t2.jsonl"
        _, report = run_scenario(
            "slo-burn", seed=3, ticks=30, trace=TraceWriter(str(p1))
        )
        slo = report["slo"]["rules"]["cloud-circuit-open"]
        assert slo["breaches"] >= 1 and slo["recoveries"] >= 1
        assert slo["status"] == "ok" and slo["breached_s"] > 0.0
        led = [l for l in read_trace(str(p1)) if l["t"] == "led"]
        breaches = [l for l in led if l["type"] == "SLOBreach"]
        recoveries = [l for l in led if l["type"] == "SLORecovered"]
        assert breaches and recoveries
        assert breaches[0]["trace_id"].startswith("tick-")
        assert recoveries[0]["tick"] > breaches[0]["tick"]
        assert breaches[0]["attrs"]["rule"] == "cloud-circuit-open"
        # counts agree across the three surfaces: report slo section,
        # report cluster_events, and the trace's led lines
        counts = report["cluster_events"]["counts"]
        assert counts["SLOBreach"] == len(breaches) == slo["breaches"]
        assert counts["SLORecovered"] == len(recoveries) == slo["recoveries"]
        # replay: byte-identical trace (led lines included), equal report
        _, replayed, recorded = replay(str(p1), trace=TraceWriter(str(p2)))
        assert p1.read_text() == p2.read_text()
        assert recorded == replayed == report

    def test_chaos_soak_declares_the_acceptance_rule(self):
        from karpenter_tpu.sim.runner import SCENARIOS

        for name in ("chaos-soak", "api-storm+catalog-roll", "slo-burn"):
            scn = SCENARIOS[name](80)
            assert any(
                r.name == "cloud-circuit-open" and r.signal == "circuits_open"
                for r in scn.slo_rules
            ), name
