"""Admission validation (webhook analogue) + link-controller adoption."""

import pytest

from karpenter_tpu.api import Disruption, NodeClass, NodePool, Requirement, Requirements, Taint
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import SelectorTerm
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.api.validation import (
    ValidationError,
    default_node_pool,
    validate_node_class,
    validate_node_pool,
)
from karpenter_tpu.testing import Environment


class TestValidation:
    def test_valid_pool_passes(self):
        validate_node_pool(NodePool(name="p", node_class_ref="d"))

    def test_missing_node_class_ref(self):
        with pytest.raises(ValidationError, match="nodeClassRef"):
            validate_node_pool(NodePool(name="p"))

    def test_restricted_requirement_key(self):
        pool = NodePool(
            name="p",
            node_class_ref="d",
            requirements=Requirements(
                [Requirement(L.LABEL_HOSTNAME, Op.IN, ["n1"])]
            ),
        )
        with pytest.raises(ValidationError, match="restricted"):
            validate_node_pool(pool)

    def test_invalid_taint_effect(self):
        pool = NodePool(
            name="p", node_class_ref="d",
            taints=[Taint(key="k", effect="Sideways")],
        )
        with pytest.raises(ValidationError, match="taint effect"):
            validate_node_pool(pool)

    def test_invalid_budget(self):
        pool = NodePool(
            name="p", node_class_ref="d",
            disruption=Disruption(budgets=["150%"]),
        )
        with pytest.raises(ValidationError, match="percentage"):
            validate_node_pool(pool)

    def test_bad_consolidation_policy(self):
        pool = NodePool(
            name="p", node_class_ref="d",
            disruption=Disruption(consolidation_policy="Sometimes"),
        )
        with pytest.raises(ValidationError, match="consolidationPolicy"):
            validate_node_pool(pool)

    def test_legacy_defaults(self):
        pool = default_node_pool(
            NodePool(name="p", node_class_ref="d"), legacy_defaults=True
        )
        assert pool.requirements.get(L.LABEL_OS).has("linux")
        assert pool.requirements.get(L.LABEL_ARCH).has("amd64")
        assert pool.requirements.get(L.LABEL_CAPACITY_TYPE).has(
            L.CAPACITY_TYPE_ON_DEMAND
        )

    def test_v1beta1_no_defaults(self):
        pool = default_node_pool(NodePool(name="p", node_class_ref="d"))
        assert pool.requirements.get(L.LABEL_CAPACITY_TYPE) is None

    def test_custom_family_needs_selectors(self):
        with pytest.raises(ValidationError, match="custom"):
            validate_node_class(NodeClass(name="c", image_family="custom"))

    def test_selector_term_id_exclusive(self):
        nc = NodeClass(
            name="c",
            subnet_selector_terms=[SelectorTerm.of(id="subnet-1", Name="x")],
        )
        with pytest.raises(ValidationError, match="mix id"):
            validate_node_class(nc)

    def test_admission_enforced_on_put(self):
        env = Environment()
        with pytest.raises(ValidationError):
            env.kube.put_node_pool(NodePool(name="bad"))
        assert "bad" not in env.kube.node_pools


class TestLink:
    def test_adopts_tagged_orphan_instance(self):
        env = Environment()
        env.default_node_class()
        pool = env.default_node_pool()
        # an instance launched out-of-band with our pool tags (e.g. a
        # previous controller generation) and no claim
        instances, _ = env.cloud.create_fleet(
            overrides=[
                {"instance_type": "std1.large", "zone": "zone-a",
                 "subnet_id": "subnet-0"}
            ],
            capacity_type=L.CAPACITY_TYPE_ON_DEMAND,
            tags={
                L.ANNOTATION_MANAGED_BY: "karpenter-tpu",
                "karpenter.sh/nodepool": pool.name,
                "Name": "orphan-node",
            },
        )
        env.step(40.0)  # past the GC grace period: link must win the race
        claims = list(env.kube.node_claims.values())
        assert len(claims) == 1
        claim = claims[0]
        assert claim.provider_id == instances[0].id
        assert claim.pool_name == pool.name
        assert claim.capacity.cpu > 0  # hydrated from the catalog
        # NOT garbage collected
        assert env.cloud.instances[instances[0].id].state == "running"

    def test_duplicate_name_tags_adopt_distinct_claims(self):
        """Two instances sharing a Name tag must both get claims — a
        collision would leave one unclaimed and GC would reap it."""
        env = Environment()
        env.default_node_class()
        pool = env.default_node_pool()
        instances, _ = env.cloud.create_fleet(
            overrides=[
                {"instance_type": "std1.large", "zone": "zone-a",
                 "subnet_id": "subnet-0"},
                {"instance_type": "std1.large", "zone": "zone-b",
                 "subnet_id": "subnet-1"},
            ],
            capacity_type=L.CAPACITY_TYPE_ON_DEMAND,
            count=2,
            tags={
                L.ANNOTATION_MANAGED_BY: "karpenter-tpu",
                "karpenter.sh/nodepool": pool.name,
                "Name": "shared-name",
            },
        )
        assert len(instances) == 2
        env.step(40.0)
        claimed_ids = {
            c.provider_id for c in env.kube.node_claims.values()
        }
        assert claimed_ids == {i.id for i in instances}
        for i in instances:
            assert env.cloud.instances[i.id].state == "running"

    def test_untagged_instance_still_reaped(self):
        env = Environment()
        env.default_node_class()
        env.default_node_pool()
        instances, _ = env.cloud.create_fleet(
            overrides=[
                {"instance_type": "std1.large", "zone": "zone-a",
                 "subnet_id": "subnet-0"}
            ],
            capacity_type=L.CAPACITY_TYPE_ON_DEMAND,
            tags={L.ANNOTATION_MANAGED_BY: "karpenter-tpu"},  # no pool tag
        )
        env.step(40.0)
        assert env.cloud.instances[instances[0].id].state == "terminated"
