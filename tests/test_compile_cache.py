"""Host-path performance layer: the solver's incremental compile cache,
the overlapped live-join continuation, the per-phase latency breakdown,
and the device-constant LRU.

The compile cache memoizes (partition groups, CompiledProblem, live-join
reservations) per (catalog snapshot, pending-set, live-node-state)
fingerprint.  The invalidation contract under test:

- warm second solve of an IDENTICAL pending set hits the cache;
- catalog epoch roll (the instance-type provider returns a new list
  object) produces a fresh compile;
- pool mutation (in-place field reassignment — the NodePool __setattr__
  epoch) produces a fresh compile;
- in-place pod mutation (field reassignment — the Pod __setattr__ epoch)
  produces a fresh compile, with no stale feasibility rows;
- live-node change (a pod binds, `used` moves) produces a fresh compile.
"""

import pytest

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm
from karpenter_tpu.scheduling import TensorScheduler
from karpenter_tpu.state.cluster import StateNode
from karpenter_tpu.testing import Environment


@pytest.fixture(scope="module")
def env():
    return Environment()


@pytest.fixture(scope="module")
def setup(env):
    pool = env.default_node_pool()
    nc = env.default_node_class()
    types = env.instance_types.list(pool, nc)
    return pool, types


def _pods(n=20, cpu=1):
    return [Pod(requests=Resources(cpu=cpu, memory="1Gi")) for _ in range(n)]


def _placed(result):
    return sum(len(vn.pods) for vn in result.new_nodes) + len(
        result.existing_placements
    )


class TestCompileCache:
    def test_warm_identical_solve_hits(self, setup):
        pool, types = setup
        pods = _pods()
        ts = TensorScheduler([pool], {pool.name: types})
        r1 = ts.solve(pods)
        assert (ts.compile_cache_hits, ts.compile_cache_misses) == (0, 1)
        r2 = ts.solve(pods)
        assert (ts.compile_cache_hits, ts.compile_cache_misses) == (1, 1)
        # the cached compile decodes the same placements
        assert _placed(r1) == _placed(r2) == len(pods)
        assert len(r1.new_nodes) == len(r2.new_nodes)

    def test_different_batch_misses(self, setup):
        pool, types = setup
        ts = TensorScheduler([pool], {pool.name: types})
        ts.solve(_pods())
        ts.solve(_pods())  # NEW pod objects: a different pending set
        assert ts.compile_cache_hits == 0
        assert ts.compile_cache_misses == 2

    def test_catalog_epoch_roll_invalidates(self, setup, env):
        pool, types = setup
        pods = _pods()
        ts = TensorScheduler([pool], {pool.name: types})
        ts.solve(pods)
        # the provider contract: inventory change = a NEW list object
        ts.instance_types = {pool.name: list(types)}
        ts.solve(pods)
        assert ts.compile_cache_hits == 0
        assert ts.compile_cache_misses == 2

    def test_pool_mutation_invalidates(self, setup):
        pool, types = setup
        pods = _pods()
        ts = TensorScheduler([pool], {pool.name: types})
        ts.solve(pods)
        pool.weight = pool.weight  # in-place reassignment bumps the epoch
        ts.solve(pods)
        assert ts.compile_cache_hits == 0
        assert ts.compile_cache_misses == 2

    def test_pod_mutation_invalidates_no_stale_rows(self, setup):
        pool, types = setup
        pods = _pods(n=5)
        ts = TensorScheduler([pool], {pool.name: types})
        r1 = ts.solve(pods)
        assert not r1.unschedulable
        # in-place mutation: an impossible node selector (a DEFINED
        # topology key, impossible value) must produce a FRESH compile
        # whose feasibility rows reject the pod — a stale cached row
        # would keep placing it
        pods[0].node_selector = {L.LABEL_ZONE: "zone-nowhere"}
        r2 = ts.solve(pods)
        assert ts.compile_cache_misses == 2
        assert pods[0].key() in r2.unschedulable
        assert _placed(r2) == len(pods) - 1

    def test_update_clears_cache(self, setup):
        pool, types = setup
        pods = _pods()
        ts = TensorScheduler([pool], {pool.name: types})
        ts.solve(pods)
        ts.update([pool], {pool.name: list(types)})
        assert not ts._compile_cache

    def test_existing_node_change_invalidates(self, setup):
        pool, types = setup
        pods = _pods(n=6)
        sn = StateNode(
            name="live-1",
            provider_id="fake://live-1",
            labels={L.LABEL_ZONE: "zone-a", L.LABEL_NODEPOOL: pool.name},
            taints=[],
            allocatable=Resources(cpu=16, memory="64Gi", pods=110),
            pods=[],
            used=Resources(),
        )
        ts = TensorScheduler([pool], {pool.name: types}, existing=[sn])
        r1 = ts.solve(pods)
        assert len(r1.existing_placements) == len(pods)
        # a pod binds: `used` is replaced, the next solve must recompile
        sn.used = sn.used + Resources(cpu=15)
        r2 = ts.solve(pods)
        assert ts.compile_cache_misses == 2
        assert len(r2.existing_placements) < len(pods)

    def test_live_taint_change_invalidates(self, setup):
        """Cordoning-by-taint in place (what the termination/disruption
        controllers do) must produce a fresh compile — taints are part of
        the live-node content fingerprint."""
        from karpenter_tpu.api import Taint

        pool, types = setup
        pods = _pods(n=4)
        sn = StateNode(
            name="live-t",
            provider_id="fake://live-t",
            labels={L.LABEL_ZONE: "zone-a", L.LABEL_NODEPOOL: pool.name},
            taints=[],
            allocatable=Resources(cpu=16, memory="64Gi", pods=110),
            pods=[],
            used=Resources(),
        )
        ts = TensorScheduler([pool], {pool.name: types}, existing=[sn])
        r1 = ts.solve(pods)
        assert len(r1.existing_placements) == len(pods)
        sn.taints = [Taint(key="k", value="v", effect="NoSchedule")]
        r2 = ts.solve(pods)
        assert ts.compile_cache_misses == 2
        assert not r2.existing_placements  # intolerable node: all new nodes

    def test_snapshot_rebuild_same_content_hits(self, setup):
        """The real controller rebuilds StateNode wrappers from
        Cluster.snapshot() every tick; content-identical wrappers (same
        name/used/labels/bound pods) must HIT — wrapper identity alone
        must not defeat the cache across reconcile ticks."""
        pool, types = setup
        pods = _pods(n=6)
        bound = Pod(labels={"a": "b"}, requests=Resources(cpu=1))

        def wrapper():
            return StateNode(
                name="live-s",
                provider_id="fake://live-s",
                labels={L.LABEL_ZONE: "zone-a", L.LABEL_NODEPOOL: pool.name},
                taints=[],
                allocatable=Resources(cpu=16, memory="64Gi", pods=110),
                pods=[bound],  # same BOUND pod objects, fresh wrapper
                used=Resources(cpu=1),
            )

        ts = TensorScheduler([pool], {pool.name: types}, existing=[wrapper()])
        r1 = ts.solve(pods)
        ts.existing = [wrapper()]  # tick 2: fresh snapshot, same content
        r2 = ts.solve(pods)
        assert (ts.compile_cache_hits, ts.compile_cache_misses) == (1, 1)
        assert r1.existing_placements == r2.existing_placements

    def test_cache_bounded(self, setup):
        pool, types = setup
        ts = TensorScheduler([pool], {pool.name: types})
        for _ in range(ts._COMPILE_CACHE_CAP + 4):
            ts.solve(_pods(n=2))
        assert len(ts._compile_cache) <= ts._COMPILE_CACHE_CAP


def _live_join_fixture(pool, n_groups=3, group_size=4):
    """Live nodes each holding one labeled bound pod, plus pending
    co-location groups whose hostname affinity selects those bound pods —
    the join-continuation shape."""
    existing, pods = [], []
    for g in range(n_groups):
        bound = Pod(
            labels={"pair": f"g{g}"}, requests=Resources(cpu=1, memory="2Gi")
        )
        existing.append(
            StateNode(
                name=f"live-{g}",
                provider_id=f"fake://live-{g}",
                labels={
                    L.LABEL_ZONE: "zone-a",
                    L.LABEL_NODEPOOL: pool.name,
                },
                taints=[],
                allocatable=Resources(cpu=16, memory="64Gi", pods=110),
                pods=[bound],
                used=Resources(cpu=1, memory="2Gi"),
            )
        )
        term = PodAffinityTerm(
            topology_key=L.LABEL_HOSTNAME,
            label_selector=(("pair", f"g{g}"),),
        )
        for _ in range(group_size):
            pods.append(
                Pod(
                    labels={"pair": f"g{g}"},
                    requests=Resources(cpu=1, memory="2Gi"),
                    pod_affinity=[term],
                )
            )
    return existing, pods


class TestLiveJoinContinuation:
    def test_join_places_groups_on_anchors(self, setup):
        pool, types = setup
        existing, pods = _live_join_fixture(pool)
        filler = _pods(n=30)
        ts = TensorScheduler([pool], {pool.name: types}, existing=existing)
        r = ts.solve(filler + pods)
        assert ts.last_path == "hybrid"
        assert ts.last_continuation == "join"
        assert not r.unschedulable
        # every group member joined ITS anchor node
        for p in pods:
            g = p.labels["pair"][1:]
            assert r.existing_placements[p.key()] == f"live-{g}"

    def test_join_respects_anchor_capacity(self, setup):
        """Groups that cannot fit their anchor fall back to the oracle
        continuation (the semantics definition) instead of overcommitting."""
        pool, types = setup
        existing, pods = _live_join_fixture(pool, n_groups=1, group_size=4)
        existing[0].allocatable = Resources(cpu=2, memory="8Gi", pods=110)
        ts = TensorScheduler([pool], {pool.name: types}, existing=existing)
        r = ts.solve(_pods(n=10) + pods)
        assert ts.last_continuation == "oracle"
        # the oracle decides: nothing lands on the overfull anchor beyond
        # its capacity
        placed_here = [
            k for k, n in r.existing_placements.items() if n == "live-0"
        ]
        total = existing[0].used + Resources(cpu=1, memory="2Gi").scaled(
            len(placed_here)
        )
        assert total.fits(existing[0].allocatable)

    def test_join_ineligible_with_extra_constraints(self, setup):
        """A joiner carrying a preference must take the oracle path (the
        join fast path only understands plain hostname-affinity)."""
        from karpenter_tpu.api import Requirement
        from karpenter_tpu.api.requirements import Op

        pool, types = setup
        existing, pods = _live_join_fixture(pool, n_groups=2)
        pods[0].preferred_affinity = [
            Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])
        ]
        ts = TensorScheduler([pool], {pool.name: types}, existing=existing)
        r = ts.solve(_pods(n=10) + pods)
        assert ts.last_path == "hybrid"
        assert ts.last_continuation == "oracle"
        assert not r.unschedulable

    def test_join_matches_oracle_placements(self, setup):
        """The fast path and the forced-oracle continuation agree on the
        join shape (the parity contract of the overlap)."""
        pool, types = setup
        existing, pods = _live_join_fixture(pool)
        batch = _pods(n=10) + pods
        ts = TensorScheduler([pool], {pool.name: types}, existing=existing)
        r_join = ts.solve(batch)
        assert ts.last_continuation == "join"
        # force the sequential oracle by disabling the plan
        ts2 = TensorScheduler([pool], {pool.name: types}, existing=existing)
        ts2._plan_live_join = lambda *a, **k: None
        r_oracle = ts2.solve(batch)
        assert ts2.last_continuation == "oracle"
        assert r_join.existing_placements == r_oracle.existing_placements
        assert not r_join.unschedulable and not r_oracle.unschedulable


class TestPhaseBreakdown:
    def test_phases_recorded_and_disjoint(self, setup):
        import time

        pool, types = setup
        pods = _pods(n=40)
        ts = TensorScheduler([pool], {pool.name: types})
        t0 = time.perf_counter()
        ts.solve(pods)
        wall = time.perf_counter() - t0
        phases = ts.last_phases
        for name in ("partition", "compile", "dispatch",
                     "device_block", "decode", "other"):
            assert name in phases, (name, phases)
        # the first solve full-tensorizes, then packs from the freshly
        # seeded resident buffers — so "delta" replaces the "pad" phase
        # whenever the backend is resident-capable (the pad/upload work
        # is exactly what the resident path deletes)
        if ts.last_resident:
            assert "delta" in phases, phases
        else:
            assert "pad" in phases, phases
        assert all(v >= 0.0 for v in phases.values()), phases
        # disjoint self-times: the sum equals the solve's wall clock
        # (within scheduling noise of the two outer perf_counter reads)
        assert sum(phases.values()) <= wall * 1.05 + 1e-3

    def test_hybrid_records_oracle_phase(self, setup):
        pool, types = setup
        existing, pods = _live_join_fixture(pool)
        ts = TensorScheduler([pool], {pool.name: types}, existing=existing)
        ts.solve(pods)
        assert "oracle" in ts.last_phases

    def test_provisioner_exports_phase_metrics(self):
        from karpenter_tpu.api import Resources as R

        env = Environment()
        env.default_node_class()
        env.default_node_pool()
        env.kube.put_pod(Pod(requests=R(cpu=1, memory="1Gi")))
        env.settle(max_rounds=10)
        samples = env.registry.histogram(
            "karpenter_solver_phase_seconds", {"phase": "compile"}
        )
        assert samples, "no phase histogram observed after a provision tick"
        assert all(s >= 0.0 for s in samples)


class TestDeviceConstantLRU:
    def test_evicts_least_recently_used_only(self):
        import numpy as np

        from karpenter_tpu.ops.packer import (
            _DEVICE_CACHE_CAP,
            cached_device_put,
        )

        cache = {}
        srcs = []
        for i in range(_DEVICE_CACHE_CAP):
            a = np.array([i], np.float32)
            srcs.append(a)
            cached_device_put(cache, (a,), (), lambda a=a: a)
        assert len(cache) == _DEVICE_CACHE_CAP
        # touch the OLDEST entry, then insert one more: the second-oldest
        # must be evicted, the touched survivor must stay resident
        cached_device_put(cache, (srcs[0],), (), lambda: srcs[0])
        extra = np.array([99], np.float32)
        cached_device_put(cache, (extra,), (), lambda: extra)
        assert len(cache) == _DEVICE_CACHE_CAP
        keys = set(cache)
        assert (id(srcs[0]),) in keys, "recently-used entry was evicted"
        assert (id(srcs[1]),) not in keys, "LRU entry survived eviction"
        assert (id(extra),) in keys

    def test_hit_returns_cached_device_value(self):
        import numpy as np

        from karpenter_tpu.ops.packer import cached_device_put

        cache = {}
        a = np.array([1.0], np.float32)
        built = []
        fn = lambda: (built.append(1), a)[1]  # noqa: E731
        v1 = cached_device_put(cache, (a,), (), fn)
        v2 = cached_device_put(cache, (a,), (), fn)
        assert v1 is v2
        assert len(built) == 1
