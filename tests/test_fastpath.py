"""Admission fast path (docs/designs/admission-fastpath.md): the twin
contract (fast path on/off converge to identical placements), the
eligibility boundary (constrained shapes fall back with the right
counted reason and NEVER mis-nominate), the singleton batch-window
bypass, the single-pod-trickle sim scenario's byte-identity with the
fast path live, and the doctor's fallback-storm / verdict-mismatch
rules over a forged flight dump."""

import pytest

from karpenter_tpu.api import Pod, Resources, Settings
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm, reset_name_sequences
from karpenter_tpu.scheduling import TensorScheduler, fastpath
from karpenter_tpu.state.cluster import StateNode
from karpenter_tpu.testing import Environment

SIZE = Resources(cpu=0.25, memory="512Mi")
ZONES = ("zone-a", "zone-b", "zone-c")


def _live_node(pool_name: str, i: int, pods=()) -> StateNode:
    return StateNode(
        name=f"live-{i}",
        provider_id=f"fake://live-{i}",
        labels={
            L.LABEL_ZONE: ZONES[i % len(ZONES)],
            L.LABEL_NODEPOOL: pool_name,
        },
        taints=[],
        allocatable=Resources(cpu=64, memory="256Gi", pods=110),
        pods=list(pods),
        used=Resources(),
    )


def _warm_scheduler(n_nodes: int = 4, seed_pods: int = 4):
    """A resident-warm TensorScheduler over live headroom: the unit-test
    twin of the provisioner's synced scheduler.  The seed batch is kept
    small so every later refresh's churn (drops the seeds, adds the
    arrivals) stays inside the delta planner's budget, while still
    opening enough node-slot bucket headroom for a max-size burst."""
    env = Environment()
    pool = env.default_node_pool()
    nc = env.default_node_class()
    types = env.instance_types.list(pool, nc)
    existing = [_live_node(pool.name, i) for i in range(n_nodes)]
    ts = TensorScheduler([pool], {pool.name: types}, existing=existing)
    ts.solve([Pod(name=f"seed-{i}", requests=SIZE) for i in range(seed_pods)])
    assert ts._resident.states, "seed solve must warm the resident plane"
    return ts


# ------------------------------------------------------------ happy path
class TestAdmit:
    def test_single_fresh_pod_nominated_onto_live_node(self):
        ts = _warm_scheduler()
        pod = Pod(name="arrival-1", requests=SIZE)
        res = fastpath.try_admit(ts, [pod])
        assert res.outcome == "nominated", (res.outcome, res.reason)
        assert set(res.placements) == {pod.key()}
        assert res.placements[pod.key()].startswith("live-")

    def test_full_solve_agrees_with_the_nomination(self):
        """The convergence contract at its smallest: the authoritative
        batched solve, run right after a fast-path verdict on the SAME
        scheduler, places the pod on the IDENTICAL node (and opens no
        new ones)."""
        ts = _warm_scheduler()
        pod = Pod(name="arrival-2", requests=SIZE)
        res = fastpath.try_admit(ts, [pod])
        assert res.outcome == "nominated", (res.outcome, res.reason)
        result = ts.solve([pod])
        assert not result.new_nodes
        assert result.existing_placements[pod.key()] == res.placements[
            pod.key()
        ]

    def test_tiny_burst_single_class_nominated(self):
        ts = _warm_scheduler()
        pods = [
            Pod(name=f"burst-{i}", requests=SIZE)
            for i in range(fastpath.FASTPATH_MAX_BURST)
        ]
        res = fastpath.try_admit(ts, pods)
        assert res.outcome == "nominated", (res.outcome, res.reason)
        assert set(res.placements) == {p.key() for p in pods}
        result = ts.solve(pods)
        assert not result.new_nodes
        assert result.existing_placements == res.placements


# ----------------------------------------------------- eligibility fence
class TestEligibilityBoundary:
    """Every ineligible shape falls back with ITS reason, and no fallback
    ever carries placements — the fence can refuse, never mis-nominate."""

    def _assert_fallback(self, res, reason):
        assert res.outcome == "fallback", (res.outcome, res.reason)
        assert res.reason == reason
        assert res.placements == {}

    def test_burst_too_large(self):
        ts = _warm_scheduler()
        pods = [
            Pod(name=f"big-{i}", requests=SIZE)
            for i in range(fastpath.FASTPATH_MAX_BURST + 1)
        ]
        self._assert_fallback(
            fastpath.try_admit(ts, pods), fastpath.REASON_BURST_TOO_LARGE
        )

    def test_mixed_class_burst(self):
        ts = _warm_scheduler()
        pods = [
            Pod(name="mix-a", requests=SIZE),
            Pod(name="mix-b", requests=Resources(cpu=2, memory="4Gi")),
        ]
        self._assert_fallback(
            fastpath.try_admit(ts, pods), fastpath.REASON_MIXED_BURST
        )

    def test_constrained_pod_shape(self):
        ts = _warm_scheduler()
        pod = Pod(
            name="affine",
            requests=SIZE,
            pod_affinity=[
                PodAffinityTerm(topology_key="kubernetes.io/hostname")
            ],
        )
        self._assert_fallback(
            fastpath.try_admit(ts, [pod]), fastpath.REASON_POD_SHAPE
        )

    def test_affinity_carrier_on_a_live_node(self):
        ts = _warm_scheduler()
        carrier = Pod(
            name="bound-carrier",
            requests=SIZE,
            pod_affinity=[
                PodAffinityTerm(topology_key="kubernetes.io/hostname")
            ],
        )
        ts.existing.append(_live_node("default", 99, pods=[carrier]))
        self._assert_fallback(
            fastpath.try_admit(ts, [Pod(name="a-3", requests=SIZE)]),
            fastpath.REASON_AFFINITY_CARRIER,
        )

    def test_catalog_roll_in_flight(self):
        ts = _warm_scheduler()
        # a pool mutation bumps the epoch half of the catalog key
        # (ops/resident._catalog_key), obsoleting every resident state
        ts.pools[0].__dict__["_mut"] = (
            ts.pools[0].__dict__.get("_mut", 0) + 1
        )
        self._assert_fallback(
            fastpath.try_admit(ts, [Pod(name="a-4", requests=SIZE)]),
            fastpath.REASON_CATALOG_ROLL,
        )

    def test_resident_cold(self):
        env = Environment()
        pool = env.default_node_pool()
        nc = env.default_node_class()
        types = env.instance_types.list(pool, nc)
        ts = TensorScheduler(
            [pool], {pool.name: types},
            existing=[_live_node(pool.name, 0)],
        )
        self._assert_fallback(
            fastpath.try_admit(ts, [Pod(name="a-5", requests=SIZE)]),
            fastpath.REASON_RESIDENT_COLD,
        )

    def test_pod_that_fits_no_live_node_needs_new_node(self):
        ts = _warm_scheduler()
        res = fastpath.try_admit(
            ts, [Pod(name="huge", requests=Resources(cpu=65, memory="1Gi"))]
        )
        assert res.outcome in ("fallback",), res.outcome
        assert res.reason in (
            fastpath.REASON_NEEDS_NEW_NODE,
            fastpath.REASON_RESIDENT_MISS,
        ), res.reason
        assert res.placements == {}

    def test_fuzz_boundary_never_mis_nominates(self):
        """Deterministic fuzz over the eligibility boundary: whatever mix
        of constrained/oversized/mixed arrivals hits the fence, a
        non-nominated verdict NEVER carries placements, and every
        nominated verdict is confirmed by the authoritative solve."""
        ts = _warm_scheduler()
        cases = [
            [Pod(name="f-0", requests=SIZE)],
            [Pod(name="f-1", requests=SIZE),
             Pod(name="f-2", requests=Resources(cpu=1, memory="1Gi"))],
            [Pod(name="f-3", requests=SIZE,
                 pod_affinity=[PodAffinityTerm(topology_key="zone")])],
            [Pod(name=f"f-4-{i}", requests=SIZE) for i in range(12)],
            [Pod(name="f-5", requests=Resources(cpu=4096))],
            [Pod(name="f-6", requests=Resources(cpu=0.25, memory="512Mi"))],
        ]
        for pods in cases:
            res = fastpath.try_admit(ts, pods)
            assert res.outcome != "mismatch", res.reason
            if res.outcome != "nominated":
                assert res.placements == {}
                continue
            result = ts.solve(pods)
            assert result.existing_placements == res.placements


# ------------------------------------------------------------- twin test
def _run_cluster(fastpath_on: bool):
    """Drive one cluster through a seed batch + a single-pod trickle and
    return (env, pod -> node placement map)."""
    reset_name_sequences()
    env = Environment(
        settings=Settings(
            cluster_name="test",
            enable_admission_fastpath=fastpath_on,
            provision_fastpath_bypass=fastpath_on,
        )
    )
    env.default_node_class()
    env.default_node_pool()
    # seed batch: 12 pods whose 2.5-cpu requests never tile a power-of-2
    # shape exactly, so every launched node keeps headroom for trickles
    for i in range(12):
        env.kube.put_pod(
            Pod(name=f"seed-{i:02d}", requests=Resources(cpu=2.5,
                                                         memory="2Gi"))
        )
    env.settle()
    assert not env.kube.pending_pods()
    # trickle: one fresh pod at a time, identically named in both twins
    for i in range(6):
        env.kube.put_pod(
            Pod(name=f"trickle-{i}", requests=Resources(cpu=0.1,
                                                        memory="64Mi"))
        )
        env.step(2.0)
    env.settle()
    assert not env.kube.pending_pods()
    placements = {k: p.node_name for k, p in sorted(env.kube.pods.items())}
    return env, placements


# -------------------------------------------------- tick trust window
class TestTickTrustWindow:
    """The resident cache's note_sync window (ops/resident.py): the
    provisioner computes the tick-wide invariants once per sync, and
    every admission inside the window skips the O(cluster) rescan.
    These tests pin the three-sided contract: rigor without a window,
    trust inside one, and witness self-invalidation when the node set
    changes under it."""

    def _carrier(self) -> Pod:
        return Pod(
            name="bound-carrier",
            requests=SIZE,
            pod_affinity=[
                PodAffinityTerm(topology_key="kubernetes.io/hostname")
            ],
        )

    def test_no_window_detects_inplace_carrier(self):
        """A raw caller that never opened a window keeps the rigorous
        per-call carrier scan — in-place node mutation included."""
        ts = _warm_scheduler()
        ts.existing[0].pods.append(self._carrier())
        res = fastpath.try_admit(ts, [Pod(name="tw-1", requests=SIZE)])
        assert res.outcome == "fallback"
        assert res.reason == fastpath.REASON_AFFINITY_CARRIER

    def test_window_trusts_cached_invariants(self):
        """Inside an open window the carrier scan is NOT re-run: the
        caller's contract is that nodes are not mutated mid-window, so
        an (illegal) in-place mutation goes unseen until re-sync — and
        a re-sync sees it again."""
        ts = _warm_scheduler()
        ts._resident.note_sync(ts)
        ts.existing[0].pods.append(self._carrier())
        res = fastpath.try_admit(ts, [Pod(name="tw-2", requests=SIZE)])
        assert res.outcome == "nominated", (res.outcome, res.reason)
        # the next sync recomputes the invariants over the mutated nodes
        ts._resident.note_sync(ts)
        res = fastpath.try_admit(ts, [Pod(name="tw-3", requests=SIZE)])
        assert res.outcome == "fallback"
        assert res.reason == fastpath.REASON_AFFINITY_CARRIER

    def test_window_witness_invalidates_on_node_set_change(self):
        """Changing the node SET under an open window (append of a new
        node carrying a carrier pod) fails the witness — the admission
        falls back to the rigorous scan and sees the carrier without
        any re-sync."""
        ts = _warm_scheduler()
        ts._resident.note_sync(ts)
        ts.existing.append(
            _live_node("default", 99, pods=[self._carrier()])
        )
        res = fastpath.try_admit(ts, [Pod(name="tw-4", requests=SIZE)])
        assert res.outcome == "fallback"
        assert res.reason == fastpath.REASON_AFFINITY_CARRIER

    def test_window_admissions_match_windowless(self):
        """The window is a pure cache: the same arrival sequence with
        and without note_sync nominates onto the same nodes."""
        ts_a = _warm_scheduler()
        ts_b = _warm_scheduler()
        ts_a._resident.note_sync(ts_a)
        for i in range(4):
            ra = fastpath.try_admit(
                ts_a, [Pod(name=f"tw-p{i}", requests=SIZE)]
            )
            rb = fastpath.try_admit(
                ts_b, [Pod(name=f"tw-p{i}", requests=SIZE)]
            )
            assert ra.outcome == rb.outcome == "nominated"
            assert list(ra.placements.values()) == list(
                rb.placements.values()
            )


def test_twin_fast_on_off_identical_eventual_placements():
    """THE twin test: the identical arrival sequence, fast path on vs
    off, must converge to the identical pod -> node map.  The fast
    half must actually have used the fast path (nominated > 0, zero
    mismatches); the slow half must never have touched it."""
    env_fast, placed_fast = _run_cluster(fastpath_on=True)
    env_slow, placed_slow = _run_cluster(fastpath_on=False)
    assert placed_fast == placed_slow
    reg = env_fast.registry
    assert reg.counter(
        "karpenter_admission_fastpath_total", {"outcome": "nominated"}
    ) > 0
    assert reg.counter("karpenter_admission_fastpath_mismatch_total") == 0
    # the latency histogram splits by admission path
    assert reg.histogram(
        "karpenter_admission_latency_seconds", {"path": "fast"}
    )
    slow_reg = env_slow.registry
    for outcome in ("nominated", "fallback", "mismatch"):
        assert slow_reg.counter(
            "karpenter_admission_fastpath_total", {"outcome": outcome}
        ) == 0
    assert not slow_reg.histogram(
        "karpenter_admission_latency_seconds", {"path": "fast"}
    )
    assert slow_reg.histogram(
        "karpenter_admission_latency_seconds", {"path": "batch"}
    )


# ------------------------------------------------------- singleton bypass
class TestSingletonBypass:
    def _env(self, bypass: bool) -> Environment:
        env = Environment(
            settings=Settings(
                cluster_name="test",
                enable_admission_fastpath=False,
                provision_fastpath_bypass=bypass,
            )
        )
        env.default_node_class()
        env.default_node_pool()
        return env

    def test_lone_pod_skips_the_batch_window(self):
        env = self._env(bypass=True)
        env.kube.put_pod(Pod(name="lone", requests=SIZE))
        env.step(0.1)  # far inside the idle window
        assert env.kube.node_claims, (
            "a lone fresh pod has nothing to coalesce with: the bypass "
            "must release it to the solve immediately"
        )

    def test_bypass_off_waits_for_idle(self):
        env = self._env(bypass=False)
        env.kube.put_pod(Pod(name="lone", requests=SIZE))
        env.step(0.1)
        assert not env.kube.node_claims
        env.step(1.1)  # idle elapsed -> the batched solve runs
        assert env.kube.node_claims

    def test_bypass_only_fires_for_singletons(self):
        env = self._env(bypass=True)
        for i in range(2):
            env.kube.put_pod(Pod(name=f"pair-{i}", requests=SIZE))
        env.step(0.1)
        assert not env.kube.node_claims  # two pods: the window coalesces


# ------------------------------------------------------------ sim plane
@pytest.mark.sim
def test_single_pod_trickle_byte_identical(tmp_path):
    """The fast path's acceptance scenario: run/run and run/replay are
    byte-identical WITH the fast path nominating live traffic, and the
    convergence invariant (mismatch counter pinned at 0) holds every
    tick."""
    from karpenter_tpu.sim.runner import replay, run_scenario
    from karpenter_tpu.sim.trace import TraceWriter

    path = str(tmp_path / "trickle.jsonl")
    w1 = TraceWriter(path)
    runner, r1 = run_scenario("single-pod-trickle", seed=11, ticks=60,
                              trace=w1)
    assert r1["invariants"]["violations"] == []
    reg = runner.env.operator.provisioner.registry
    assert reg.counter(
        "karpenter_admission_fastpath_total", {"outcome": "nominated"}
    ) > 0, "the trickle scenario must exercise the fast path"
    assert reg.counter("karpenter_admission_fastpath_mismatch_total") == 0
    # run/run determinism
    w2 = TraceWriter()
    _, r2 = run_scenario("single-pod-trickle", seed=11, ticks=60, trace=w2)
    assert w1.text() == w2.text()
    assert r1 == r2
    # record/replay byte-identity (no generators in the loop)
    w3 = TraceWriter()
    _, replayed, recorded = replay(path, trace=w3)
    assert recorded == r1
    assert replayed == r1
    assert w3.text() == open(path).read()


# --------------------------------------------------------------- doctor
def test_doctor_names_fallback_storm_and_mismatch(tmp_path):
    """A forged flight dump with a late fallback storm (dominant reason
    catalog_roll) and one verdict mismatch: doctor must name both,
    citing the dominant reason and the convergence contract."""
    from karpenter_tpu.metrics.registry import Registry
    from karpenter_tpu.obs.context import set_tick
    from karpenter_tpu.obs.doctor import diagnose
    from karpenter_tpu.obs.events import EventLedger
    from karpenter_tpu.obs.flight import FlightRecorder, load_flight
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    reg = Registry()
    led = EventLedger(clock=clock, registry=reg)
    reg.ledger = led
    fr = FlightRecorder(clock, reg, ledger=led, capacity=64)
    try:
        for i in range(24):
            clock.step(1.0)
            set_tick(f"tick-{i + 1:06d}")
            if i >= 16:  # the storm: past the doctor's split index
                for _ in range(2):
                    reg.inc(
                        "karpenter_admission_fastpath_total",
                        {"outcome": "fallback"},
                    )
                    reg.inc(
                        "karpenter_admission_fastpath_fallback_total",
                        {"reason": "catalog_roll"},
                    )
            if i == 20:
                reg.inc("karpenter_admission_fastpath_mismatch_total")
            fr.record(i + 1, f"tick-{i + 1:06d}", 0.01, {"pending": 0})
        path = tmp_path / "flight-fastpath.jsonl"
        fr.dump(str(path), trigger="manual")
    finally:
        set_tick("")
    diag = diagnose(load_flight(str(path)))
    causes = diag["suspected_causes"]
    (storm,) = [c for c in causes if "fallback storm" in c]
    assert "catalog_roll" in storm
    assert "16 fallback(s)" in storm
    (mismatch,) = [c for c in causes if "verdict" in c and "mismatch" in c]
    assert "convergence contract" in mismatch
