"""Requirements-algebra semantics (reference: karpenter-core
scheduling.Requirements as used at pkg/cloudprovider/cloudprovider.go:301-306)."""

from karpenter_tpu.api import Op, Requirement, Requirements


def test_in_has():
    r = Requirement("zone", Op.IN, ["a", "b"])
    assert r.has("a") and r.has("b") and not r.has("c")


def test_not_in_has():
    r = Requirement("zone", Op.NOT_IN, ["a"])
    assert not r.has("a") and r.has("b")


def test_exists_does_not_exist():
    assert Requirement("k", Op.EXISTS).has("anything")
    assert not Requirement("k", Op.DOES_NOT_EXIST).has("anything")


def test_gt_lt():
    gt = Requirement("cpu", Op.GT, ["4"])
    assert gt.has("8") and not gt.has("4") and not gt.has("2")
    lt = Requirement("cpu", Op.LT, ["4"])
    assert lt.has("2") and not lt.has("4")
    assert not gt.has("not-a-number")


def test_intersection_in_in():
    a = Requirement("z", Op.IN, ["a", "b"])
    b = Requirement("z", Op.IN, ["b", "c"])
    got = a.intersection(b)
    assert got.has("b") and not got.has("a") and not got.has("c")
    assert a.intersects(b)
    assert not a.intersects(Requirement("z", Op.IN, ["c"]))


def test_intersection_in_notin():
    a = Requirement("z", Op.IN, ["a", "b"])
    b = Requirement("z", Op.NOT_IN, ["a"])
    got = a.intersection(b)
    assert got.has("b") and not got.has("a")


def test_intersection_gt_with_in():
    a = Requirement("cpu", Op.IN, ["2", "4", "8"])
    b = Requirement("cpu", Op.GT, ["2"])
    assert a.intersects(b)
    merged = a.intersection(b)
    assert merged.has("4") and not merged.has("2")


def test_allows_absent():
    assert Requirement("k", Op.NOT_IN, ["x"]).allows_absent()
    assert Requirement("k", Op.DOES_NOT_EXIST).allows_absent()
    assert not Requirement("k", Op.EXISTS).allows_absent()
    assert not Requirement("k", Op.IN, ["x"]).allows_absent()
    assert not Requirement("k", Op.GT, ["1"]).allows_absent()


def test_requirements_add_intersects():
    reqs = Requirements([Requirement("z", Op.IN, ["a", "b"])])
    reqs.add(Requirement("z", Op.NOT_IN, ["a"]))
    assert reqs.get("z").has("b") and not reqs.get("z").has("a")


def test_compatible_missing_key():
    node = Requirements([Requirement("zone", Op.IN, ["a"])])
    # incoming In on undefined key -> incompatible
    assert not node.compatible(Requirements([Requirement("gpu", Op.IN, ["true"])]))
    # incoming NotIn/DoesNotExist on undefined key -> compatible
    assert node.compatible(Requirements([Requirement("gpu", Op.NOT_IN, ["true"])]))
    assert node.compatible(Requirements([Requirement("gpu", Op.DOES_NOT_EXIST)]))
    # shared key must intersect
    assert node.compatible(Requirements([Requirement("zone", Op.IN, ["a", "b"])]))
    assert not node.compatible(Requirements([Requirement("zone", Op.IN, ["b"])]))


def test_from_labels_and_node_selector_terms():
    reqs = Requirements.from_labels({"zone": "a"})
    assert reqs.get("zone").has("a") and not reqs.get("zone").has("b")
    reqs2 = Requirements.from_node_selector_terms(
        [{"key": "zone", "operator": "In", "values": ["a", "b"]}]
    )
    assert reqs2.get("zone").has("b")


def test_unsatisfiable():
    reqs = Requirements([Requirement("z", Op.IN, ["a"])])
    reqs.add(Requirement("z", Op.IN, ["b"]))
    assert reqs.is_unsatisfiable()


def test_labels_projection():
    reqs = Requirements(
        [Requirement("a", Op.IN, ["x"]), Requirement("b", Op.NOT_IN, ["y"])]
    )
    assert reqs.labels() == {"a": "x"}


def test_does_not_exist_is_satisfiable():
    # regression: DoesNotExist is an empty allow-list but satisfiable by absence
    assert not Requirements([Requirement("gpu", Op.DOES_NOT_EXIST)]).is_unsatisfiable()
    # while In ∩ In = ∅ is genuinely unsatisfiable
    r = Requirements([Requirement("z", Op.IN, ["a"])])
    r.add(Requirement("z", Op.IN, ["b"]))
    assert r.is_unsatisfiable()
    # DoesNotExist ∩ In = unsatisfiable (must be absent AND present)
    r2 = Requirements([Requirement("z", Op.DOES_NOT_EXIST)])
    r2.add(Requirement("z", Op.IN, ["a"]))
    assert r2.is_unsatisfiable()


def test_contradictory_bounds():
    gt = Requirement("cpu", Op.GT, ["5"])
    lt = Requirement("cpu", Op.LT, ["3"])
    assert not gt.intersects(lt)
    r = Requirements([gt])
    r.add(lt)
    assert r.is_unsatisfiable()
    # non-contradictory bounds still intersect
    assert Requirement("cpu", Op.GT, ["2"]).intersects(Requirement("cpu", Op.LT, ["8"]))


def test_min_values_survives_intersection():
    r = Requirements([Requirement("t", Op.EXISTS, min_values=3)])
    r.add(Requirement("t", Op.NOT_IN, ["t1"]))
    assert r.get("t").min_values == 3
