"""Image-family bootstrappers (reference pkg/providers/amifamily/bootstrap/):
each family emits a DISTINCT user-data format, deterministically, and the
resolver picks format + block-device defaults from the family registry."""

import pytest

from karpenter_tpu.api import NodeClass, NodePool
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import BlockDeviceMapping, Taint
from karpenter_tpu.providers.bootstrap import (
    BootstrapConfig,
    CustomBootstrap,
    ShellBootstrap,
    TomlBootstrap,
    parse_settings,
)
from karpenter_tpu.providers.image import FAMILIES, image_family
from karpenter_tpu.testing import Environment


def _cfg(**kw):
    base = dict(
        cluster_name="prod",
        cluster_endpoint="https://api.prod:443",
        labels={"team": "ml", "tier": "batch"},
        taints=[
            Taint("dedicated", "gpu", "NoSchedule"),
            Taint("boot", "pending", "NoExecute"),
        ],
    )
    base.update(kw)
    return BootstrapConfig(**base)


class TestShellBootstrap:
    def test_mime_structure_and_kubelet_args(self):
        s = ShellBootstrap(_cfg(max_pods=58)).script()
        assert s.startswith("MIME-Version: 1.0")
        assert 'boundary="//"' in s
        assert "/etc/node/bootstrap.sh 'prod'" in s
        assert "--apiserver-endpoint 'https://api.prod:443'" in s
        # explicit density disables the derived default and pins max-pods
        assert "--use-max-pods false" in s
        assert "--max-pods=58" in s
        assert '--node-labels="team=ml,tier=batch"' in s
        assert (
            '--register-with-taints="boot=pending:NoExecute,dedicated=gpu:NoSchedule"'
            in s
        )
        assert s.rstrip().endswith("--//--")

    def test_custom_user_data_rides_first(self):
        s = ShellBootstrap(_cfg(custom_user_data="echo pre-hook")).script()
        assert s.index("echo pre-hook") < s.index("/etc/node/bootstrap.sh")

    def test_premimed_custom_data_not_double_wrapped(self):
        inner = ShellBootstrap(_cfg(custom_user_data="echo inner")).script()
        s = ShellBootstrap(_cfg(custom_user_data=inner)).script()
        assert s.count("MIME-Version: 1.0") == 1
        assert "echo inner" in s

    def test_foreign_boundary_mime_parts_spliced(self):
        """Externally generated multipart docs use their own boundary;
        their parts must be spliced through, not dropped."""
        foreign = (
            "MIME-Version: 1.0\n"
            'Content-Type: multipart/mixed; boundary="==B=="\n'
            "\n"
            "--==B==\n"
            'Content-Type: text/x-shellscript; charset="us-ascii"\n'
            "\n"
            "echo userhook\n"
            "--==B==--\n"
        )
        s = ShellBootstrap(_cfg(custom_user_data=foreign)).script()
        assert "echo userhook" in s
        assert s.count("MIME-Version: 1.0") == 1

    def test_deterministic_under_input_order(self):
        a = _cfg(labels={"a": "1", "b": "2"})
        b = _cfg(labels={"b": "2", "a": "1"})
        b.taints = list(reversed(b.taints))
        assert ShellBootstrap(a).script() == ShellBootstrap(b).script()


class TestTomlBootstrap:
    def test_settings_document(self):
        s = TomlBootstrap(_cfg(max_pods=110)).script()
        doc = parse_settings(s)
        k8s = doc["settings.kubernetes"]
        assert k8s["cluster-name"] == '"prod"'
        assert k8s["api-server"] == '"https://api.prod:443"'
        assert k8s["max-pods"] == "110"
        assert doc["settings.kubernetes.node-labels"]['"team"'] == '"ml"'
        taints = doc["settings.kubernetes.node-taints"]
        assert taints['"dedicated"'] == '["gpu:NoSchedule"]'

    def test_controller_settings_overwrite_custom(self):
        custom = (
            "[settings.host]\nmotd = \"hi\"\n"
            "[settings.kubernetes]\ncluster-name = \"spoofed\"\n"
        )
        s = TomlBootstrap(_cfg(custom_user_data=custom)).script()
        doc = parse_settings(s)
        # user additions survive, controller-owned keys win
        assert doc["settings.host"]["motd"] == '"hi"'
        assert doc["settings.kubernetes"]["cluster-name"] == '"prod"'

    def test_reserved_resources(self):
        s = TomlBootstrap(
            _cfg(kube_reserved={"cpu": "100m"}, eviction_hard={"memory.available": "5%"})
        ).script()
        doc = parse_settings(s)
        assert doc["settings.kubernetes.kube-reserved"]['"cpu"'] == '"100m"'
        assert (
            doc["settings.kubernetes.eviction-hard"]['"memory.available"'] == '"5%"'
        )


class TestCustomBootstrap:
    def test_verbatim_passthrough(self):
        raw = "#cloud-config\nruncmd:\n  - my-own-bootstrap\n"
        assert CustomBootstrap(_cfg(custom_user_data=raw)).script() == raw


class TestFamilyRegistry:
    def test_formats_are_distinct(self):
        cfg = _cfg()
        shell = FAMILIES["standard"].bootstrapper(cfg).script()
        toml = FAMILIES["accelerated"].bootstrapper(cfg).script()
        assert "MIME-Version" in shell and "MIME-Version" not in toml
        assert "[settings.kubernetes]" in toml and "[settings" not in shell

    def test_block_device_defaults_differ(self):
        std = FAMILIES["standard"].block_device_defaults
        acc = FAMILIES["accelerated"].block_device_defaults
        assert len(std) == 1 and std[0].device_name == "/dev/xvda"
        assert len(acc) == 2  # small OS volume + data volume
        assert acc[0].volume_size < acc[1].volume_size
        assert FAMILIES["custom"].block_device_defaults == ()

    def test_unknown_family_falls_back_to_standard(self):
        nc = NodeClass(name="x", image_family="does-not-exist")
        assert image_family(nc).name == "standard"


class TestResolverIntegration:
    @pytest.fixture()
    def env(self):
        return Environment()

    def _specs(self, env, family, **nc_kw):
        nc = env.default_node_class()
        nc.image_family = family
        for k, v in nc_kw.items():
            setattr(nc, k, v)
        pool = env.default_node_pool()
        its = env.instance_types.list(pool, nc)[:5]
        return env.operator.resolver.resolve(
            nc, pool, its, cluster_name="prod", cluster_endpoint="https://e"
        )

    def test_standard_resolves_shell_and_default_volume(self, env):
        specs = self._specs(env, "standard")
        assert specs
        for sp in specs:
            assert sp.user_data.startswith("MIME-Version")
            assert [b.device_name for b in sp.block_device_mappings] == [
                "/dev/xvda"
            ]

    def test_accelerated_resolves_toml_and_two_volumes(self, env):
        specs = self._specs(env, "accelerated")
        assert specs
        for sp in specs:
            assert "[settings.kubernetes]" in sp.user_data
            assert len(sp.block_device_mappings) == 2

    def test_node_class_mappings_override_family_default(self, env):
        override = [BlockDeviceMapping(device_name="/dev/sdz")]
        specs = self._specs(env, "accelerated", block_device_mappings=override)
        for sp in specs:
            assert [b.device_name for b in sp.block_device_mappings] == [
                "/dev/sdz"
            ]

    def test_empty_image_resolution_fails_launch_loudly(self, env):
        """No resolvable image (all deprecated) must FAIL the launch with
        NoImageResolvedError — never boot a template-less, unconfigured
        machine (reference resolver.go:118-127 'no amis exist given
        constraints')."""
        from karpenter_tpu.api import Pod, Resources

        env.default_node_class()
        env.default_node_pool()
        for im in env.cloud.images.values():
            im.deprecated = True
        env.operator.images.invalidate()
        env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="1Gi")))
        for _ in range(3):
            env.step(2.0)
        assert env.kube.pending_pods()  # isolated failure, pod waits
        running = [
            i for i in env.cloud.instances.values() if i.state == "running"
        ]
        assert not running

    def test_max_pods_rides_in_user_data(self, env):
        nc = env.default_node_class()
        pool = env.default_node_pool(kubelet_max_pods=42)
        its = env.instance_types.list(pool, nc)[:3]
        specs = env.operator.resolver.resolve(
            nc, pool, its, cluster_name="prod", cluster_endpoint="https://e"
        )
        assert specs and all("--max-pods=42" in sp.user_data for sp in specs)
