from karpenter_tpu.api import Resources
from karpenter_tpu.api.resources import parse_quantity


def test_parse_quantity():
    assert parse_quantity("100m") == 0.1
    assert parse_quantity("1Gi") == 2**30
    assert parse_quantity("2") == 2.0
    assert parse_quantity(3) == 3.0
    assert parse_quantity("1.5") == 1.5
    assert parse_quantity("2k") == 2000.0


def test_arithmetic_and_fits():
    a = Resources(cpu="500m", memory="1Gi")
    b = Resources(cpu="250m", memory="512Mi")
    s = a + b
    assert s.cpu == 0.75 and s.memory == 1.5 * 2**30
    cap = Resources(cpu=1, memory="2Gi")
    assert s.fits(cap)
    assert not (s + a).fits(cap)
    # axes absent from the request never block
    assert Resources(cpu="100m").fits(cap)
    # but axes absent from capacity do
    assert not Resources(gpu=1).fits(cap)


def test_exceeds_limits():
    usage = Resources(cpu=10)
    assert usage.exceeds(Resources(cpu=8))
    assert not usage.exceeds(Resources(cpu=16))
    assert not usage.exceeds(Resources())  # empty limit = unlimited


def test_vector_projection():
    r = Resources(cpu=2, memory="4Gi")
    assert r.as_vector(["cpu", "memory", "pods"]) == (2.0, 4 * 2**30, 0.0)


def test_parse_quantity_errors_and_small_suffixes():
    import pytest

    assert parse_quantity("10u") == pytest.approx(1e-5)
    assert parse_quantity("100n") == pytest.approx(1e-7)
    with pytest.raises(ValueError):
        parse_quantity("1g")  # unknown suffix -> ValueError, not KeyError
    with pytest.raises(ValueError):
        parse_quantity("1Qx")


def test_explicit_zero_limit_blocks():
    # `limits: {cpu: 0}` = provision nothing, not unlimited
    zero_limit = Resources(cpu=0)
    assert not zero_limit.is_zero() or True  # presence is what matters
    assert Resources(cpu=1).exceeds(zero_limit)
    assert not Resources(cpu=1).exceeds(Resources())  # truly empty = unlimited
