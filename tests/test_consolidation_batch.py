"""Batched consolidation what-ifs: parity, fallback, and cache hygiene.

The contract under test (docs/designs/consolidation-batching.md): the
batched evaluation path (`TensorScheduler.evaluate_removals` — one compiled
base problem + one vmapped verdict dispatch per batch) must be
DECISION-IDENTICAL to the sequential per-subset simulation
(`DisruptionController._simulate`).  Elements the batch cannot answer
bit-identically come back `needs_host` and run sequentially, so the only
acceptable difference between the two paths is speed.
"""

import random

import pytest

from karpenter_tpu.api import (
    Disruption,
    PersistentVolumeClaim,
    Pod,
    Resources,
)
from karpenter_tpu.cloud.fake.backend import generate_catalog
from karpenter_tpu.controllers.disruption import _RemovalEvaluator
from karpenter_tpu.scheduling.solver import RemovalCandidate
from karpenter_tpu.testing import Environment

SIZES = [
    Resources(cpu=0.5, memory="1Gi"),
    Resources(cpu=1, memory="2Gi"),
    Resources(cpu=2, memory="4Gi"),
]


def _build_env(seed: int, npods: int, cpus=(4, 8)) -> Environment:
    from karpenter_tpu.api.objects import reset_name_sequences

    reset_name_sequences()
    env = Environment(shapes=generate_catalog(generations=(1, 2), cpus=cpus))
    env.default_node_class()
    env.default_node_pool(
        disruption=Disruption(consolidation_policy="WhenUnderutilized")
    )
    rng = random.Random(seed)
    for _ in range(npods):
        env.kube.put_pod(Pod(requests=rng.choice(SIZES)))
    env.settle(max_rounds=60)
    assert not env.kube.pending_pods()
    return env


def _ranked_candidates(dc):
    dc._budgets = dc._remaining_budgets()
    return sorted(
        (c for c in dc._candidates() if dc._consolidatable(c)),
        key=lambda c: c.disruption_cost(),
    )


def _assert_parity(env, subsets):
    """Every batched verdict equals the sequential simulation's; elements
    the batch declined (`needs_host`) are exempt by construction — the
    controller runs exactly the sequential path for them."""
    dc = env.operator.disruption
    cands = _ranked_candidates(dc)
    inv = dc._pool_inventory()
    ev = _RemovalEvaluator(dc, cands, inv)
    ev._sync_scheduler()
    elements = [
        [RemovalCandidate(c.state.name, tuple(c.reschedulable)) for c in s]
        for s in subsets
    ]
    verdicts = dc._scheduler.evaluate_removals(elements, ev._universe)
    answered = 0
    for s, v in zip(subsets, verdicts):
        fits, price, _vn = dc._simulate(list(s), inv)
        names = tuple(c.claim.name for c in s)
        if v.needs_host:
            continue
        answered += 1
        assert v.fits == fits, (names, v, (fits, price))
        assert v.replacement_price == pytest.approx(price, abs=1e-9), (
            names, v, (fits, price),
        )
    return answered, len(subsets)


def _parity_subsets(cands):
    """The shapes the controller actually evaluates: the single-node scan,
    the prefix floor, and drop-one children of the top set."""
    subsets = [[c] for c in cands]
    subsets += [cands[:k] for k in range(2, min(len(cands), 8) + 1)]
    top = cands[:6]
    if len(top) > 2:
        subsets += [top[:i] + top[i + 1:] for i in range(len(top))]
    return subsets


@pytest.mark.parametrize("seed,npods", [(0, 120), (2, 90)])
def test_parity_seeded_cluster(seed, npods):
    env = _build_env(seed, npods, cpus=(4, 8) if seed == 0 else (8, 16, 32))
    cands = _ranked_candidates(env.operator.disruption)
    assert len(cands) >= 3
    answered, total = _assert_parity(env, _parity_subsets(cands))
    # the batch must actually answer the bulk of the pass, or the whole
    # mechanism is a fallback in disguise
    assert answered >= total * 0.6, (answered, total)


@pytest.mark.sim
@pytest.mark.parametrize("scenario_name", ["diurnal", "chaos-soak"])
def test_parity_sim_snapshot(scenario_name):
    """Mid-run snapshots of real simulator scenarios: the batched verdicts
    match the sequential simulations on the cluster states the scenarios
    actually produce (chaos faults, diurnal churn), not just on synthetic
    fixtures.  The stock catalog packs everything onto 1-2 large nodes, so
    the scenario runs on small shapes with extra steady load — same
    workloads, same chaos schedule, a fleet wide enough to consolidate."""
    from karpenter_tpu.sim.runner import SCENARIOS, ScenarioRunner
    from karpenter_tpu.sim.workload import Steady

    scn = SCENARIOS[scenario_name](60)
    scn.shapes = generate_catalog(generations=(1, 2), cpus=(4, 8))
    scn.workloads.append(Steady(rate=2.0))
    runner = ScenarioRunner(scn, seed=3, ticks=60)
    for t in range(50):
        events = [
            ev
            for w in scn.workloads
            for ev in w.events(t, runner.rng, runner.view)
        ]
        dt = (
            runner.rng.choice(list(scn.tick_jitter))
            if scn.tick_jitter
            else scn.tick_s
        )
        runner._tick(t, dt, "run", events)
    env = runner.env
    cands = _ranked_candidates(env.operator.disruption)
    if len(cands) < 2:
        pytest.skip("scenario snapshot produced too few candidates")
    _assert_parity(env, _parity_subsets(cands))


def test_forced_fallback_identical_decisions():
    """Flipping the batched path off must not change ANY consolidation
    decision: two identically-seeded clusters — one batched, one forced
    sequential — take the same actions tick for tick."""
    digests = []
    for batched in (True, False):
        env = _build_env(5, 110)
        dc = env.operator.disruption
        dc.use_batched_consolidation = batched
        # a small population keeps the sequential twin's per-mask
        # `_simulate` walk inside the suite budget; BOTH twins share the
        # shape (the knobs size the search plan, never the backend), and
        # tests/test_consolidation_search.py covers the search itself
        dc.search_rounds = 2
        dc.search_population = 12
        rng = random.Random(99)
        keys = sorted(env.kube.pods.keys())
        for key in rng.sample(keys, len(keys) // 2):
            env.kube.delete_pod(key)
        states = []
        for _ in range(25):
            env.clock.step(65)
            env.step(2.0)
            states.append(
                (
                    tuple(sorted(
                        name
                        for name, cl in env.kube.node_claims.items()
                        if cl.deleted_at is not None
                    )),
                    tuple(sorted(dc._pending)),
                    tuple(sorted(
                        (p.key(), p.node_name or "")
                        for p in env.kube.pods.values()
                    )),
                )
            )
        digests.append(states)
        # the batched run must actually have used the batched path
        evals = env.registry.counters.get(
            "karpenter_consolidation_evals_total", {}
        )
        by_path = {k[0][1]: v for k, v in evals.items() if k}
        if batched:
            assert by_path.get("batched", 0) > 0, by_path
        assert (
            env.registry.counter(
                "karpenter_consolidation_verdict_mismatch_total"
            )
            == 0
        )
    assert digests[0] == digests[1]


def test_consolidation_pass_leaves_provisioner_cache_warm():
    """Satellite regression: a consolidation pass must not mutate shared
    live pods (volume re-resolution now lands on copies), so the
    provisioner's identity+epoch compile-cache fingerprint still hits on
    the next solve."""
    env = Environment(shapes=generate_catalog(generations=(1, 2), cpus=(4, 8)))
    env.default_node_class()
    env.default_node_pool(
        disruption=Disruption(consolidation_policy="WhenUnderutilized")
    )
    # an unbound WaitForFirstConsumer-style claim: at provisioning time it
    # resolves to nothing, and BINDS to a zone afterwards — the exact case
    # where consolidation's re-resolution differs from the stored value
    env.kube.pvcs["default/pvc-1"] = PersistentVolumeClaim(name="pvc-1")
    pods = [Pod(requests=Resources(cpu=1, memory="2Gi")) for _ in range(30)]
    pods[0].volume_claims = ["pvc-1"]
    for p in pods:
        env.kube.put_pod(p)
    env.settle(max_rounds=40)
    assert not env.kube.pending_pods()
    bound = env.kube.pods[pods[0].key()]
    zone = env.kube.nodes[bound.node_name].labels.get(
        "topology.kubernetes.io/zone", "zone-a"
    )
    env.kube.pvcs["default/pvc-1"].bound_zone = zone

    dc = env.operator.disruption
    epochs = {p.key(): p.mutation_epoch() for p in env.kube.pods.values()}
    stored = list(bound.volume_requirements)
    dc._budgets = dc._remaining_budgets()
    cands = _ranked_candidates(dc)
    assert any(
        any(q.key() == bound.key() for q in c.reschedulable) for c in cands
    )
    inv = dc._pool_inventory()
    for c in cands:
        dc._simulate([c], inv)
    dc._simulate(cands, inv)
    # shared pods untouched: same epochs, same stored requirements — the
    # freshly-bound zone was honored on a COPY inside the simulation
    assert {
        p.key(): p.mutation_epoch() for p in env.kube.pods.values()
    } == epochs
    assert list(bound.volume_requirements) == stored

    # and the provisioner's compile cache stays warm across a full
    # disruption reconcile: solve, reconcile disruption, solve again
    prov = env.operator.provisioner
    blocker = Pod(requests=Resources(cpu=10_000))  # pends forever
    env.kube.put_pod(blocker)
    prov.provision([blocker])
    hits0 = prov.scheduler.compile_cache_hits
    misses0 = prov.scheduler.compile_cache_misses
    dc.reconcile()
    prov.provision([blocker])
    assert prov.scheduler.compile_cache_hits == hits0 + 1
    assert prov.scheduler.compile_cache_misses == misses0


def test_removal_base_cache_warm_across_calls():
    """The batched base problem compiles ONCE per (universe, cluster
    state): repeated evaluations — descent levels, single scan, repeat
    reconciles over an unchanged cluster — reuse the cached compile."""
    env = _build_env(1, 80)
    dc = env.operator.disruption
    cands = _ranked_candidates(dc)
    assert len(cands) >= 2
    ev = _RemovalEvaluator(dc, cands, dc._pool_inventory())
    ev._sync_scheduler()
    sched = dc._scheduler
    universe = ev._universe
    base1 = sched._removal_base(universe)
    assert not base1.reason
    base2 = sched._removal_base(universe)
    assert base1 is base2
    # an in-place pod mutation invalidates the fingerprint
    cands[0].reschedulable[0].labels = {"mutated": "yes"}
    base3 = sched._removal_base(universe)
    assert base3 is not base1


def test_consolidation_metrics_recorded():
    env = _build_env(4, 100)
    dc = env.operator.disruption
    for _ in range(3):
        env.clock.step(65)
        env.step(2.0)
    reg = env.registry
    evals = reg.counters.get("karpenter_consolidation_evals_total", {})
    assert sum(evals.values()) > 0
    by_path = {k[0][1]: v for k, v in evals.items() if k}
    assert by_path.get("batched", 0) > 0, by_path
    sizes = reg.histogram("karpenter_consolidation_eval_batch_size")
    assert sizes and max(sizes) >= 2
    assert reg.counter("karpenter_consolidation_verdict_mismatch_total") == 0
    # compile-cache visibility: both consumers export the _total series
    assert (
        sum(
            reg.counters.get(
                "karpenter_solver_compile_cache_misses_total", {}
            ).values()
        )
        > 0
    )
