"""Controller integration tests: the full loop over the fake cloud.

The analogue of the reference's envtest suites (suite_test.go pattern):
real provisioner + real cloudprovider + fake cloud + fake clock, driving
pending pods to running nodes and back down through every deprovisioning
mechanism (SURVEY.md §3.2-3.4, §4).
"""

import pytest

from karpenter_tpu.api import Disruption, Pod, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.state.kube import PodDisruptionBudget
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    e = Environment(settings=None)
    return e


@pytest.fixture()
def ready(env):
    pool = env.default_node_pool()
    env.default_node_class()
    return pool


def add_pods(env, n, cpu=1, memory="1Gi", **kw):
    pods = [Pod(requests=Resources(cpu=cpu, memory=memory), **kw) for _ in range(n)]
    for p in pods:
        env.kube.put_pod(p)
    return pods


class TestProvisioning:
    def test_batch_window_waits_for_idle(self, env, ready):
        add_pods(env, 10)
        env.step(0.5)  # window open, not idle yet
        assert not env.kube.node_claims
        env.step(1.1)  # idle elapsed -> solve + launch
        assert env.kube.node_claims
        assert all(c.launched for c in env.kube.node_claims.values())

    def test_pods_bind_to_new_nodes(self, env, ready):
        add_pods(env, 50)
        env.settle()
        assert not env.kube.pending_pods()
        assert env.kube.nodes
        for p in env.kube.pods.values():
            assert p.node_name in env.kube.nodes

    def test_in_flight_claims_prevent_double_provisioning(self, env, ready):
        add_pods(env, 20)
        env.step(0.1)  # window opens on first observation
        env.step(1.1)  # idle elapsed -> launch
        claims_1 = set(env.kube.node_claims)
        assert claims_1
        env.step(1.1)  # pods nominated; no duplicate launch
        assert set(env.kube.node_claims) == claims_1

    def test_daemonset_overhead_reserved(self, env, ready):
        ds = Pod(
            requests=Resources(cpu=0.5, memory="512Mi"),
            is_daemonset=True,
        )
        env.kube.put_pod(ds)
        add_pods(env, 5)
        env.settle()
        # every launched node leaves room for the daemonset
        for c in env.kube.node_claims.values():
            assert c.allocatable.cpu >= 0.5

    def test_pool_limits_block_launch(self, env):
        env.default_node_class()
        env.default_node_pool(limits=Resources(cpu=2))
        add_pods(env, 200, cpu=4)
        env.step(0.1)
        env.step(1.1)
        # limits cap provisioning at SOLVE time: the pool only offers
        # types within its headroom (<=2 cpu), so nothing that fits a
        # 4-cpu pod can launch and the pods report unschedulable
        assert not env.kube.node_claims
        assert any(
            e[1] == "FailedScheduling" for e in env.kube.events
        )

    def test_unschedulable_pod_emits_event(self, env, ready):
        add_pods(env, 1, cpu=100000)
        env.step(0.1)
        env.step(1.1)
        assert any(e[1] == "FailedScheduling" for e in env.kube.events)

    def test_ice_failure_retries_next_batch(self, env, ready):
        # all spot+od pools for every type in zone-a..c insufficient for the
        # cheapest family: the fleet falls back inside create_fleet; force
        # total failure by marking ALL pools insufficient
        for t in env.cloud.shapes:
            for z in env.cloud.zones:
                env.cloud.mark_insufficient(t, z, L.CAPACITY_TYPE_SPOT)
                env.cloud.mark_insufficient(t, z, L.CAPACITY_TYPE_ON_DEMAND)
        add_pods(env, 3)
        env.step(0.1)
        env.step(1.1)
        assert not env.kube.node_claims
        # capacity recovers
        env.cloud.insufficient_pools.clear()
        env.unavailable.flush()
        env.settle()
        assert env.kube.node_claims
        assert not env.kube.pending_pods()


class TestLifecycle:
    def test_register_initialize_conditions(self, env, ready):
        add_pods(env, 3)
        env.settle()
        for c in env.kube.node_claims.values():
            assert c.registered and c.initialized
        for n in env.kube.nodes.values():
            assert n.labels[L.LABEL_NODE_REGISTERED] == "true"
            assert n.labels[L.LABEL_NODE_INITIALIZED] == "true"

    def test_liveness_reaps_unregistered_claims(self, ready):
        pass  # exercised in the startup-delay environment below

    def test_liveness_with_startup_delay(self):
        env = Environment(node_startup_delay=10_000.0)  # never registers in time
        env.default_node_class()
        env.default_node_pool()
        add_pods(env, 2)
        env.step(0.1)
        env.step(1.1)
        assert env.kube.node_claims
        ids = [c.provider_id for c in env.kube.node_claims.values()]
        env.step(16 * 60.0)  # past REGISTRATION_TTL
        assert not env.kube.node_claims
        for i in ids:
            assert env.cloud.instances[i].state == "terminated"


class TestGarbageCollection:
    def test_orphaned_instance_reaped(self, env, ready):
        env.cloud.create_fleet(
            overrides=[{"instance_type": "std1.large", "zone": "zone-a",
                        "subnet_id": "subnet-0"}],
            capacity_type="on-demand",
            tags={L.ANNOTATION_MANAGED_BY: "karpenter-tpu"},
        )
        env.step(40.0)  # past the 30s grace period
        assert all(
            i.state == "terminated" for i in env.cloud.instances.values()
        )

    def test_node_without_instance_removed(self, env, ready):
        add_pods(env, 2)
        env.settle()
        claim = next(iter(env.kube.node_claims.values()))
        # out-of-band termination
        env.cloud.terminate_instances([claim.provider_id])
        env.step(1.0)
        assert claim.name not in env.kube.node_claims
        assert env.kube.node_by_provider_id(claim.provider_id) is None


class TestTermination:
    def test_graceful_drain_and_delete(self, env, ready):
        add_pods(env, 5)
        env.settle()
        claim = next(iter(env.kube.node_claims.values()))
        env.operator.termination.mark_for_deletion(claim, reason="test")
        env.step(2.0)  # cordon + drain + terminate
        env.settle()  # evicted pods re-provision
        assert claim.name not in env.kube.node_claims
        # pods rescheduled elsewhere
        assert not env.kube.pending_pods()

    def test_do_not_evict_blocks_drain(self, env, ready):
        add_pods(env, 2, annotations={L.ANNOTATION_DO_NOT_EVICT: "true"})
        env.settle()
        claim = next(iter(env.kube.node_claims.values()))
        env.operator.termination.mark_for_deletion(claim, reason="test")
        env.step(5.0)
        # node cordoned but not terminated
        assert claim.name in env.kube.node_claims
        node = env.kube.node_by_provider_id(claim.provider_id)
        assert node is not None and node.cordoned

    def test_pdb_limits_evictions(self, env, ready):
        pods = add_pods(env, 4, labels={"app": "web"})
        env.kube.put_pdb(
            PodDisruptionBudget(
                name="web-pdb",
                label_selector={"app": "web"},
                min_available=4,  # nothing may be evicted
            )
        )
        env.settle()
        claim = next(iter(env.kube.node_claims.values()))
        env.operator.termination.mark_for_deletion(claim, reason="test")
        env.step(5.0)
        assert claim.name in env.kube.node_claims  # drain blocked


class TestInterruption:
    @pytest.fixture()
    def env(self):
        from karpenter_tpu.api import Settings

        return Environment(
            settings=Settings(cluster_name="test", interruption_queue_name="q")
        )

    def test_spot_interruption_drains_and_marks_ice(self, env, ready):
        add_pods(env, 3)
        env.settle()
        claim = next(iter(env.kube.node_claims.values()))
        env.cloud.send_message(
            {"kind": "spot_interruption", "instance_id": claim.provider_id}
        )
        env.step(2.0)  # receive + drain + terminate
        env.settle()
        assert claim.name not in env.kube.node_claims
        assert env.unavailable.is_unavailable(
            L.CAPACITY_TYPE_SPOT, claim.instance_type_name, claim.zone
        )
        # pods rescheduled
        assert not env.kube.pending_pods()

    def test_rebalance_recommendation_drains(self, env, ready):
        add_pods(env, 2)
        env.settle()
        claim = next(iter(env.kube.node_claims.values()))
        env.cloud.send_message(
            {"kind": "rebalance_recommendation", "instance_id": claim.provider_id}
        )
        env.step(2.0)
        env.settle()
        assert claim.name not in env.kube.node_claims

    def test_unknown_message_dropped(self, env, ready):
        env.cloud.send_message({"kind": "mystery"})
        env.step(1.0)
        assert not env.cloud.queue  # consumed + deleted

    def test_full_batch_drained_in_one_pass(self, env, ready):
        """A 10-message batch is processed by the worker pool in ONE
        reconcile (reference controller.go:108-118's 10-way errgroup)."""
        add_pods(env, 2)
        env.settle()
        claims = list(env.kube.node_claims.values())
        for _ in range(10):
            env.cloud.send_message(
                {"kind": "rebalance_recommendation",
                 "instance_id": claims[0].provider_id}
            )
        before = env.cloud.recorder.count("ReceiveMessage")
        env.operator.interruption.reconcile()
        assert not env.cloud.queue
        assert env.cloud.recorder.count("ReceiveMessage") == before + 1
        # 10 concurrent messages for ONE instance: exactly one disruption
        # mark (check-and-set under the termination lock), not ten
        assert (
            env.registry.counter(
                "karpenter_nodeclaims_disrupted",
                {"reason": "rebalance_recommendation", "nodepool": "default"},
            )
            == 1
        )

    def test_failed_message_isolated_and_redelivered(self, env, ready):
        """One poisoned message must not stop the batch, must stay on the
        queue for redelivery, and must succeed once the fault clears
        (controller.go:120-133 per-message error isolation)."""
        add_pods(env, 2)
        env.settle()
        claim = next(iter(env.kube.node_claims.values()))
        ic = env.operator.interruption
        poisoned = claim.provider_id
        orig = ic.termination.mark_for_deletion
        calls = {"fail": True}

        def flaky(c, reason=""):
            if calls["fail"] and c.provider_id == poisoned:
                raise RuntimeError("api throttled")
            return orig(c, reason=reason)

        ic.termination.mark_for_deletion = flaky
        env.cloud.send_message({"kind": "mystery"})  # drops cleanly
        env.cloud.send_message(
            {"kind": "scheduled_change", "instance_id": poisoned}
        )
        ic.reconcile()
        # the healthy message was consumed; the poisoned one remains
        assert [m.body["kind"] for m in env.cloud.queue] == [
            "scheduled_change"
        ]
        assert env.registry.counter(
            "karpenter_interruption_message_errors"
        ) == 1
        calls["fail"] = False
        # the failed message is IN FLIGHT until the visibility timeout
        # elapses; an immediate poll must not see it (SQS contract)
        ic.reconcile()
        assert len(env.cloud.queue) == 1
        env.clock.step(env.cloud.visibility_timeout + 1)
        ic.reconcile()  # redelivery succeeds
        assert not env.cloud.queue
        assert claim.deleted_at is not None

    def test_undeleted_message_redelivered_then_processed_exactly_once(
        self, env, ready
    ):
        """The handler succeeds but DeleteMessage fails: the message must
        stay hidden until `invisible_until`, reappear after it, be
        re-processed (idempotently) exactly once more, and never again
        after the successful deletion — the SQS visibility-timeout contract
        (cloud/fake/backend.py receive/delete)."""
        from karpenter_tpu.cloud.fake.backend import CloudAPIError

        add_pods(env, 2)
        env.settle()
        claim = next(iter(env.kube.node_claims.values()))
        ic = env.operator.interruption
        env.cloud.send_message(
            {"kind": "scheduled_change", "instance_id": claim.provider_id}
        )
        # outlast the retry layer (1 initial + cloud_max_retries attempts)
        retries = env.operator.retrying.max_retries
        env.cloud.recorder.set_error_sequence(
            "DeleteMessage",
            [CloudAPIError("InternalError")] * (retries + 1),
        )
        ic.reconcile()
        # handled (the claim was marked) but NOT deleted
        assert claim.deleted_at is not None
        assert len(env.cloud.queue) == 1
        assert env.registry.counter(
            "karpenter_interruption_message_errors"
        ) == 1
        assert env.registry.counter(
            "karpenter_interruption_deleted_messages"
        ) == 0
        # in flight: an immediate poll must not see it
        ic.reconcile()
        assert env.registry.counter(
            "karpenter_interruption_received_messages",
            {"message_type": "scheduled_change"},
        ) == 1
        # past invisible_until it reappears and is re-processed once
        env.clock.step(env.cloud.visibility_timeout + 1)
        ic.reconcile()
        assert not env.cloud.queue
        assert env.registry.counter(
            "karpenter_interruption_deleted_messages"
        ) == 1
        assert env.registry.counter(
            "karpenter_interruption_received_messages",
            {"message_type": "scheduled_change"},
        ) == 2
        # after deletion: gone for good
        ic.reconcile()
        assert env.registry.counter(
            "karpenter_interruption_received_messages",
            {"message_type": "scheduled_change"},
        ) == 2
        assert env.registry.counter(
            "karpenter_interruption_deleted_messages"
        ) == 1


class TestDisruption:
    def test_expiration(self, env):
        env.default_node_class()
        env.default_node_pool(
            disruption=Disruption(expire_after=3600.0, consolidation_policy="WhenEmpty")
        )
        add_pods(env, 2)
        env.settle()
        claims = set(env.kube.node_claims)
        assert claims
        env.step(3700.0)
        env.settle()
        # original claims replaced by fresh ones
        assert not (claims & set(env.kube.node_claims))
        assert not env.kube.pending_pods()

    def test_emptiness_when_empty_policy(self, env):
        env.default_node_class()
        env.default_node_pool(
            disruption=Disruption(
                consolidation_policy="WhenEmpty", consolidate_after=30.0
            )
        )
        pods = add_pods(env, 2)
        env.settle()
        assert env.kube.node_claims
        for p in pods:
            env.kube.delete_pod(p.key())
        env.step(40.0)
        env.step(5.0)
        assert not env.kube.node_claims  # empty nodes deleted

    def test_drift_disrupts(self, env, ready):
        add_pods(env, 2)
        env.settle()
        nc = env.kube.get_node_class("default")
        claims = set(env.kube.node_claims)
        nc.user_data = "echo drifted"  # changes the static hash
        env.settle()
        env.step(2.0)
        env.settle()
        assert not (claims & set(env.kube.node_claims))
        assert not env.kube.pending_pods()

    def test_consolidation_deletes_underutilized(self, env):
        from karpenter_tpu.api import Requirement, Requirements
        from karpenter_tpu.api.requirements import Op

        env.default_node_class()
        # cap node size so the fleet spreads over several nodes
        env.default_node_pool(
            requirements=Requirements(
                [Requirement(L.LABEL_INSTANCE_CPU, Op.LT, ["17"])]
            ),
            disruption=Disruption(consolidation_policy="WhenUnderutilized"),
        )
        pods = add_pods(env, 40, cpu=1, memory="1Gi")
        env.settle()
        before_nodes = len(env.kube.node_claims)
        assert before_nodes >= 3
        # free most pods: most nodes end up empty or nearly so
        for p in pods[4:]:
            env.kube.delete_pod(p.key())
        for _ in range(10):
            env.step(2.0)
        env.settle()
        after_nodes = len(env.kube.node_claims)
        assert after_nodes < before_nodes
        assert not env.kube.pending_pods()

    def test_spot_nodes_delete_only(self, env):
        """A lightly-loaded spot node whose pods would need a (cheaper)
        replacement node is NOT consolidated — spot is delete-only
        (deprovisioning.md:83-110)."""
        env.default_node_class()
        env.default_node_pool(
            disruption=Disruption(consolidation_policy="WhenUnderutilized")
        )
        pods = add_pods(env, 20, cpu=1, memory="1Gi")
        env.settle()
        claims = set(env.kube.node_claims)
        assert all(
            c.capacity_type == L.CAPACITY_TYPE_SPOT
            for c in env.kube.node_claims.values()
        )
        # drop to 2 pods: pure deletion can't absorb them (no other node),
        # and replacement is forbidden for spot
        for p in pods[2:]:
            env.kube.delete_pod(p.key())
        for _ in range(5):
            env.step(2.0)
        assert set(env.kube.node_claims) == claims

    def test_do_not_consolidate_annotation_blocks(self, env):
        env.default_node_class()
        env.default_node_pool(
            disruption=Disruption(consolidation_policy="WhenUnderutilized")
        )
        pods = add_pods(env, 10)
        env.settle()
        for c in env.kube.node_claims.values():
            c.annotations[L.ANNOTATION_DO_NOT_CONSOLIDATE] = "true"
        claims = set(env.kube.node_claims)
        for p in pods:
            env.kube.delete_pod(p.key())
        for _ in range(5):
            env.step(2.0)
        assert set(env.kube.node_claims) == claims  # annotation respected

    def test_pdb_max_unavailable_counts_pending_replacements(self, env, ready):
        """An evicted-but-not-yet-rescheduled pod consumes maxUnavailable,
        so a second drain pass cannot exceed the budget."""
        from karpenter_tpu.api import Pod as P

        pods = [
            P(labels={"app": "db"}, requests=Resources(cpu=1)) for _ in range(3)
        ]
        env.kube.put_pdb(
            PodDisruptionBudget(
                name="db", label_selector={"app": "db"}, max_unavailable=1
            )
        )
        for p in pods:
            env.kube.put_pod(p)
        env.settle()
        # one matching pod already down (simulates a slow replacement)
        downed = pods[0]
        downed.node_name = ""
        downed.phase = "Pending"
        pdb = env.kube.pdbs["db"]
        assert pdb.disruptions_allowed(list(env.kube.pods.values())) == 0

    def test_budget_limits_disruptions(self, env):
        env.default_node_class()
        env.default_node_pool(
            disruption=Disruption(
                consolidation_policy="WhenEmpty",
                consolidate_after=0.0,
                budgets=["1"],
            )
        )
        sel = (("app", "dense"),)
        from karpenter_tpu.api.objects import PodAffinityTerm

        pods = [
            Pod(
                labels={"app": "dense"},
                requests=Resources(cpu=0.5),
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME, label_selector=sel,
                        anti=True,
                    )
                ],
            )
            for _ in range(4)
        ]
        for p in pods:
            env.kube.put_pod(p)
        env.settle()
        assert len(env.kube.node_claims) == 4
        for p in pods:
            env.kube.delete_pod(p.key())
        env.step(2.0)  # one pass: budget allows ONE disruption
        disrupting = sum(
            1 for c in env.kube.node_claims.values() if c.deleted_at is not None
        )
        assert disrupting <= 1


class TestPoolTemplateDrift:
    def test_requirements_change_rolls_nodes(self):
        """Narrowing a pool's zone requirement drifts nodes outside it
        (karpenter-core requirements drift) and replacements land inside."""
        from karpenter_tpu.api import Requirement, Requirements
        from karpenter_tpu.api import labels as L
        from karpenter_tpu.api.requirements import Op

        env = Environment()
        env.default_node_class()
        env.default_node_pool(
            requirements=Requirements(
                [Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"])]
            )
        )
        pods = [Pod(requests=Resources(cpu=2, memory="4Gi")) for _ in range(6)]
        for p in pods:
            env.kube.put_pod(p)
        env.settle()
        assert not env.kube.pending_pods()
        before = set(env.kube.node_claims)
        assert all(
            c.zone == "zone-b" for c in env.kube.node_claims.values()
        )
        # narrow the pool to zone-c: every zone-b node is now drifted
        pool = env.kube.node_pools["default"]
        pool.requirements = Requirements(
            [Requirement(L.LABEL_ZONE, Op.IN, ["zone-c"])]
        )
        for _ in range(60):
            env.clock.step(30)
            env.step(2.0)
            live = [
                c
                for c in env.kube.node_claims.values()
                if c.zone == "zone-c"
            ]
            if (
                not env.kube.pending_pods()
                and live
                and not (set(env.kube.node_claims) & before)
            ):
                break
        assert not env.kube.pending_pods()
        assert not (set(env.kube.node_claims) & before)
        assert all(c.zone == "zone-c" for c in env.kube.node_claims.values())
