from karpenter_tpu.api import (
    InstanceType,
    NodeClass,
    NodePool,
    Offering,
    Offerings,
    Op,
    Overhead,
    Pod,
    Requirement,
    Requirements,
    Resources,
    Taint,
    Toleration,
)
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import (
    PodAffinityTerm,
    SelectorTerm,
    TopologySpreadConstraint,
    tolerates_all,
)


def test_tolerations():
    taint = Taint("team", "ml", L.TAINT_EFFECT_NO_SCHEDULE)
    assert Toleration("team", "Equal", "ml").tolerates(taint)
    assert Toleration("team", "Exists").tolerates(taint)
    assert Toleration(operator="Exists").tolerates(taint)  # wildcard
    assert not Toleration("team", "Equal", "web").tolerates(taint)
    assert not Toleration("team", "Equal", "ml", "NoExecute").tolerates(taint)
    # PreferNoSchedule is soft
    soft = Taint("x", "y", L.TAINT_EFFECT_PREFER_NO_SCHEDULE)
    assert tolerates_all([], [soft])
    assert not tolerates_all([], [taint])


def test_pod_defaults_pod_slot():
    p = Pod(requests=Resources(cpu=1))
    assert p.requests.get(L.RESOURCE_PODS) == 1


def test_pod_scheduling_requirements():
    p = Pod(
        node_selector={L.LABEL_ZONE: "z1"},
        required_affinity=[Requirement(L.LABEL_ARCH, Op.IN, ["arm64"])],
    )
    reqs = p.scheduling_requirements()
    assert reqs.get(L.LABEL_ZONE).has("z1")
    assert reqs.get(L.LABEL_ARCH).has("arm64")


def test_offerings_queries():
    offs = Offerings(
        [
            Offering("z1", "on-demand", 1.0),
            Offering("z2", "on-demand", 0.9),
            Offering("z1", "spot", 0.3, available=False),
            Offering("z2", "spot", 0.25),
        ]
    )
    assert offs.available().cheapest().price == 0.25
    reqs = Requirements([Requirement(L.LABEL_ZONE, Op.IN, ["z1"])])
    assert offs.available().compatible(reqs).cheapest().price == 1.0
    assert offs.zones() == ["z1", "z2"]


def test_instance_type_allocatable():
    it = InstanceType(
        name="std-4",
        requirements=Requirements(),
        capacity=Resources(cpu=4, memory="16Gi", pods=110),
        overhead=Overhead(
            kube_reserved=Resources(cpu="80m", memory="1Gi"),
            eviction_threshold=Resources(memory="100Mi"),
        ),
    )
    alloc = it.allocatable()
    assert abs(alloc.cpu - 3.92) < 1e-9
    assert alloc.memory == 16 * 2**30 - 2**30 - 100 * 2**20
    assert alloc.get(L.RESOURCE_PODS) == 110


def test_nodepool_template_requirements():
    pool = NodePool(
        name="default",
        labels={"team": "ml"},
        requirements=Requirements([Requirement(L.LABEL_ARCH, Op.IN, ["amd64"])]),
    )
    reqs = pool.template_requirements()
    assert reqs.get("team").has("ml")
    assert reqs.get(L.LABEL_NODEPOOL).has("default")
    assert reqs.get(L.LABEL_ARCH).has("amd64")


def test_selector_term_and_nodeclass_hash():
    term = SelectorTerm.of(environment="prod")
    assert term.matches("id-1", "n", {"environment": "prod", "x": "y"})
    assert not term.matches("id-1", "n", {"environment": "dev"})
    by_id = SelectorTerm.of(id="subnet-123")
    assert by_id.matches("subnet-123", "", {})

    nc = NodeClass(name="default", user_data="echo hi")
    h1 = nc.static_hash()
    nc.user_data = "echo bye"
    assert nc.static_hash() != h1
    nc.user_data = "echo hi"
    assert nc.static_hash() == h1


def test_selector_wildcard_requires_key():
    term = SelectorTerm.of(environment="*")
    assert term.matches("id", "n", {"environment": "anything"})
    assert not term.matches("id", "n", {})  # key must exist


class TestSelectorOperatorValidation:
    """ADVICE r5 low: an unknown matchExpressions operator keeps kube's
    invalid-selector contract (match nothing) but must surface loudly —
    once — when the object is BUILT in code, so a typo'd operator doesn't
    silently match nothing forever."""

    def test_unknown_operator_matches_nothing_and_warns_once(self, caplog):
        import logging

        from karpenter_tpu.api import objects as O

        O._warned_expr_ops.discard("Inn")  # isolate from other specs
        with caplog.at_level(logging.WARNING, logger="karpenter_tpu.api.objects"):
            tsc = TopologySpreadConstraint(
                1, "zone", match_expressions=(("k", "Inn", ("v",)),)
            )
            # match-nothing semantics preserved
            assert not tsc.selects(Pod(labels={"k": "v"}))
            warnings = [
                r for r in caplog.records if "unknown label-selector" in r.message
            ]
            assert len(warnings) == 1, warnings
            assert "Inn" in warnings[0].message
            # a second object with the same typo does not spam the log
            PodAffinityTerm(
                topology_key="zone",
                match_expressions=(("k", "Inn", ("v",)),),
            )
            warnings = [
                r for r in caplog.records if "unknown label-selector" in r.message
            ]
            assert len(warnings) == 1

    def test_valid_operators_do_not_warn(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING, logger="karpenter_tpu.api.objects"):
            PodAffinityTerm(
                topology_key="zone",
                match_expressions=(
                    ("a", "In", ("v",)),
                    ("b", "NotIn", ("v",)),
                    ("c", "Exists", ()),
                    ("d", "DoesNotExist", ()),
                ),
            )
        assert not [
            r for r in caplog.records if "unknown label-selector" in r.message
        ]
