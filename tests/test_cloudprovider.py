"""CloudProvider facade + launch-path provider tests.

Mirrors the reference suite shape (pkg/cloudprovider/suite_test.go,
pkg/providers/instance/suite_test.go): real providers over the fake cloud,
scriptable capacity errors, drift scenarios.
"""

import pytest

from karpenter_tpu.api import (
    NodeClaim,
    Requirement,
    Requirements,
    Resources,
)
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.cloud.provider import (
    DRIFT_IMAGE,
    DRIFT_NODECLASS,
    DRIFT_SECURITY_GROUP,
)
from karpenter_tpu.errors import (
    InsufficientCapacityAggregateError,
    NodeClaimNotFoundError,
)
from karpenter_tpu.providers.instance import MAX_INSTANCE_TYPES
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    return Environment()


@pytest.fixture()
def setup(env):
    pool = env.default_node_pool()
    nc = env.default_node_class()
    return pool, nc


def make_claim(pool, requirements=(), requests=None, **kw):
    reqs = Requirements(list(requirements))
    return NodeClaim(
        pool_name=pool.name,
        node_class_ref=pool.node_class_ref,
        requirements=reqs,
        requests=requests or Resources(cpu=1, memory="1Gi"),
        **kw,
    )


class TestCreate:
    def test_launches_and_projects_status(self, env, setup):
        pool, nc = setup
        claim = make_claim(pool)
        out = env.cloud_provider.create(claim)
        assert out.launched
        assert out.provider_id.startswith("i-")
        assert out.instance_type_name
        assert out.zone in env.cloud.zones
        assert out.capacity.cpu > 0
        assert out.allocatable.cpu < out.capacity.cpu  # overhead subtracted
        assert out.labels[L.LABEL_INSTANCE_TYPE] == out.instance_type_name
        assert L.ANNOTATION_NODECLASS_HASH in out.annotations
        inst = env.cloud.instances[out.provider_id]
        assert inst.tags["karpenter.sh/nodeclaim"] == claim.name

    def test_cheapest_type_launched(self, env, setup):
        pool, nc = setup
        claim = make_claim(pool)
        out = env.cloud_provider.create(claim)
        # spot is cheapest in the fake; price must be the spot price
        assert out.capacity_type == L.CAPACITY_TYPE_SPOT
        assert out.price == pytest.approx(
            env.cloud.spot_price(out.instance_type_name, out.zone)
        )

    def test_on_demand_when_required(self, env, setup):
        pool, nc = setup
        claim = make_claim(
            pool,
            [Requirement(L.LABEL_CAPACITY_TYPE, Op.IN, [L.CAPACITY_TYPE_ON_DEMAND])],
        )
        out = env.cloud_provider.create(claim)
        assert out.capacity_type == L.CAPACITY_TYPE_ON_DEMAND

    def test_zone_requirement_respected(self, env, setup):
        pool, nc = setup
        claim = make_claim(pool, [Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"])])
        out = env.cloud_provider.create(claim)
        assert out.zone == "zone-b"

    def test_instance_labels_win_over_type_projection(self, env, setup):
        """Regression: a type offered in several zones must not stamp an
        arbitrary zone label over the launched instance's actual zone —
        the claim's zone field and its labels must agree (reference
        cloudprovider.go:348-383 projects from the instance last)."""
        pool, nc = setup
        claim = make_claim(
            pool,
            [
                Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"]),
                Requirement(
                    L.LABEL_CAPACITY_TYPE, Op.IN, [L.CAPACITY_TYPE_ON_DEMAND]
                ),
            ],
            requests=Resources(cpu=28, memory="48Gi"),
        )
        out = env.cloud_provider.create(claim)
        assert out.zone == "zone-b"
        assert out.labels[L.LABEL_ZONE] == "zone-b"
        assert out.labels[L.LABEL_CAPACITY_TYPE] == L.CAPACITY_TYPE_ON_DEMAND
        # multi-valued requirement keys must not project to labels at all
        its = env.instance_types.list(pool, nc)
        multi_zone = next(t for t in its if t.name == out.instance_type_name)
        assert L.LABEL_ZONE not in multi_zone.requirements.labels()

    def test_accelerator_types_filtered_unless_requested(self, env, setup):
        pool, nc = setup
        out = env.cloud_provider.create(make_claim(pool))
        shape = env.cloud.shapes[out.instance_type_name]
        assert shape.gpu_count == 0 and shape.tpu_chips == 0

    def test_accelerator_launch_when_requested(self, env, setup):
        pool, nc = setup
        claim = make_claim(
            pool, requests=Resources({L.RESOURCE_TPU: 2, "cpu": 1, "memory": 2**30})
        )
        out = env.cloud_provider.create(claim)
        assert env.cloud.shapes[out.instance_type_name].tpu_chips >= 2

    def test_ice_feedback_marks_unavailable_and_falls_back(self, env, setup):
        pool, nc = setup
        # every zone of the cheapest spot pool is capacity-constrained
        claim = make_claim(pool)
        # find what it would launch, then mark those pools insufficient
        probe = env.cloud_provider.create(make_claim(pool))
        cheapest = probe.instance_type_name
        for z in env.cloud.zones:
            env.cloud.mark_insufficient(cheapest, z, L.CAPACITY_TYPE_SPOT)
        out = env.cloud_provider.create(claim)
        # fleet fell back to the next override; failed pools are ICE-cached
        assert out.provider_id
        assert out.instance_type_name != cheapest or out.capacity_type != (
            L.CAPACITY_TYPE_SPOT
        )
        assert env.unavailable.is_unavailable(
            L.CAPACITY_TYPE_SPOT, cheapest, out.zone
        ) or any(
            env.unavailable.is_unavailable(L.CAPACITY_TYPE_SPOT, cheapest, z)
            for z in env.cloud.zones
        )

    def test_all_pools_insufficient_raises(self, env, setup):
        pool, nc = setup
        claim = make_claim(
            pool,
            [Requirement(L.LABEL_INSTANCE_TYPE, Op.IN, ["std1.large"])],
        )
        for z in env.cloud.zones:
            for ct in (L.CAPACITY_TYPE_SPOT, L.CAPACITY_TYPE_ON_DEMAND):
                env.cloud.mark_insufficient("std1.large", z, ct)
        with pytest.raises(InsufficientCapacityAggregateError):
            env.cloud_provider.create(claim)

    def test_fleet_requests_coalesce(self, env, setup):
        pool, nc = setup
        from concurrent.futures import ThreadPoolExecutor

        # the FAST_BATCH_WINDOWS idle window is 2ms: under a loaded
        # machine the 8 threads can miss each other entirely, so retry
        # the burst — the assertion is that the batcher CAN coalesce,
        # not that the OS scheduler always cooperates
        for attempt in range(3):
            before = env.cloud.recorder.count("CreateFleet")
            with ThreadPoolExecutor(max_workers=8) as pool_exec:
                claims = [make_claim(pool) for _ in range(8)]
                outs = list(pool_exec.map(env.cloud_provider.create, claims))
            assert all(o.provider_id for o in outs)
            assert len({o.provider_id for o in outs}) == 8
            # identical configs merged into fewer CreateFleet calls
            if env.cloud.recorder.count("CreateFleet") - before < 8:
                return
        raise AssertionError(
            "CreateFleet never coalesced across 3 bursts of 8"
        )


class TestGetListDelete:
    def test_get_roundtrip(self, env, setup):
        pool, nc = setup
        out = env.cloud_provider.create(make_claim(pool))
        got = env.cloud_provider.get(out.provider_id)
        assert got.provider_id == out.provider_id
        assert got.instance_type_name == out.instance_type_name
        assert got.pool_name == pool.name

    def test_list_only_managed(self, env, setup):
        pool, nc = setup
        env.cloud_provider.create(make_claim(pool))
        # an unmanaged instance (no managed-by tag) must not be listed
        from karpenter_tpu.cloud.fake.backend import FakeInstance

        env.cloud.instances["i-foreign"] = FakeInstance(
            id="i-foreign", instance_type="std1.large", zone="zone-a",
            capacity_type="on-demand",
        )
        listed = env.cloud_provider.list()
        assert {c.provider_id for c in listed} != set()
        assert "i-foreign" not in {c.provider_id for c in listed}

    def test_delete_terminates(self, env, setup):
        pool, nc = setup
        out = env.cloud_provider.create(make_claim(pool))
        env.cloud_provider.delete(out)
        assert env.cloud.instances[out.provider_id].state == "terminated"
        with pytest.raises(NodeClaimNotFoundError):
            env.cloud_provider.get(out.provider_id)

    def test_delete_twice_raises_not_found(self, env, setup):
        pool, nc = setup
        out = env.cloud_provider.create(make_claim(pool))
        env.cloud_provider.delete(out)
        with pytest.raises(NodeClaimNotFoundError):
            env.cloud_provider.delete(out)


class TestDrift:
    def _launched(self, env, pool):
        claim = make_claim(pool)
        out = env.cloud_provider.create(claim)
        return out

    def test_no_drift_when_unchanged(self, env, setup):
        pool, nc = setup
        claim = self._launched(env, pool)
        assert env.cloud_provider.is_drifted(claim) == ""

    def test_nodeclass_hash_drift(self, env, setup):
        pool, nc = setup
        claim = self._launched(env, pool)
        nc.user_data = "echo changed"
        assert env.cloud_provider.is_drifted(claim) == DRIFT_NODECLASS

    def test_image_drift(self, env, setup):
        pool, nc = setup
        claim = self._launched(env, pool)
        # a newer image supersedes; the old image id is no longer resolved
        from karpenter_tpu.cloud.fake.backend import FakeImage

        for im in list(env.cloud.images.values()):
            im.deprecated = True
        env.cloud.add_image(
            FakeImage(
                id="image-new", family="standard", arch="amd64",
                created_at=env.clock.now() + 10,
            )
        )
        env.images.invalidate()
        assert env.cloud_provider.is_drifted(claim) == DRIFT_IMAGE

    def test_security_group_drift(self, env, setup):
        pool, nc = setup
        claim = self._launched(env, pool)
        from karpenter_tpu.cloud.fake.backend import FakeSecurityGroup

        env.cloud.add_security_group(
            FakeSecurityGroup(id="sg-extra", name="extra")
        )
        env.security_groups.invalidate()
        assert env.cloud_provider.is_drifted(claim) == DRIFT_SECURITY_GROUP


class TestGetInstanceTypes:
    def test_inventory_feed(self, env, setup):
        pool, nc = setup
        types = env.cloud_provider.get_instance_types(pool)
        assert len(types) > 100
        assert all(t.offerings for t in types)

    def test_price_cap_constant(self):
        assert MAX_INSTANCE_TYPES == 60
