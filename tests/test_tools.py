"""Operational tools (reference tools/): allocatable-diff + kompat."""

import csv

import pytest

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.testing import Environment
from karpenter_tpu.tools import kompat
from karpenter_tpu.tools.allocatable_diff import (
    diff_rows,
    overpromised,
    write_csv,
)


class TestAllocatableDiff:
    @pytest.fixture()
    def fleet(self):
        env = Environment()
        env.default_node_class()
        env.default_node_pool()
        for _ in range(6):
            env.kube.put_pod(Pod(requests=Resources(cpu=2, memory="4Gi")))
        env.settle()
        assert env.kube.nodes
        return env

    def test_rows_cover_every_managed_node(self, fleet):
        report = diff_rows(fleet.operator)
        rows = report.rows
        assert not report.skipped
        assert len(rows) == len(fleet.kube.nodes)
        for r in rows:
            assert r.instance_type
            assert r.expected_capacity_mem_mi > 0
            assert r.expected_alloc_mem_mi < r.expected_capacity_mem_mi

    def test_fake_kubelet_matches_model_exactly(self, fleet):
        """The fake kubelet registers nodes straight from the computed
        claim, so expected == actual and nothing is overpromised — the
        calibrated-baseline case the reference tool reports as all-zero
        diff columns."""
        rows = diff_rows(fleet.operator).rows
        assert rows and not overpromised(rows)
        for r in rows:
            assert r.alloc_mem_diff_mi == 0 and r.alloc_cpu_diff_m == 0

    def test_detects_overpromise(self, fleet):
        """Shrink a node's ACTUAL allocatable (a kubelet reserving more
        than the model assumes): the tool must flag the node."""
        node = next(iter(fleet.kube.nodes.values()))
        node.allocatable = Resources(
            cpu=node.allocatable.get("cpu") - 0.5,
            memory=f"{int(node.allocatable.get('memory')) - 2**30}",
            pods=110,
        )
        bad = overpromised(diff_rows(fleet.operator).rows)
        assert [r.node for r in bad] == [node.name]
        assert bad[0].alloc_mem_diff_mi > 0

    def test_skipped_nodes_reported(self, fleet):
        """A node whose type left the listing is a finding, not a silent
        omission."""
        node = next(iter(fleet.kube.nodes.values()))
        node.labels[L.LABEL_INSTANCE_TYPE] = "ghost.type"
        report = diff_rows(fleet.operator)
        assert node.name in report.skipped

    def test_csv_round_trip(self, fleet, tmp_path):
        rows = diff_rows(fleet.operator).rows
        out = tmp_path / "diff.csv"
        write_csv(rows, str(out))
        with open(out) as f:
            got = list(csv.reader(f))
        assert len(got) == 2 + len(rows)  # two header rows
        assert got[0][0] == "Instance Type"
        assert got[2][0] == rows[0].instance_type


MATRIX = {
    "name": "karpenter-tpu",
    "compatibility": [
        {"appVersion": "0.30.x", "minK8sVersion": "1.23", "maxK8sVersion": "1.27"},
        {"appVersion": "0.31.x", "minK8sVersion": "1.23", "maxK8sVersion": "1.28"},
        {"appVersion": "0.32.0", "minK8sVersion": "1.25", "maxK8sVersion": "1.28"},
    ],
}


class TestKompat:
    def test_compatible_within_bracket(self):
        m = kompat.parse(MATRIX)
        assert m.compatible("0.31.4", "1.28")  # x-wildcard patch
        assert m.compatible("0.31.0", "1.23")
        assert not m.compatible("0.30.2", "1.28")  # above max
        assert not m.compatible("0.32.0", "1.24")  # below min

    def test_unknown_version_raises(self):
        m = kompat.parse(MATRIX)
        with pytest.raises(KeyError):
            m.compatible("9.9.9", "1.27")

    def test_exact_version_requires_exact_match(self):
        m = kompat.parse(MATRIX)
        assert m.find("0.32.0") is not None
        assert m.find("0.32.1") is None  # no wildcard on that row

    def test_last_n_and_markdown(self):
        m = kompat.parse(MATRIX).last_n(2)
        md = m.markdown()
        lines = md.splitlines()
        assert len(lines) == 4
        assert "0.31.x" in lines[0] and "0.30.x" not in lines[0]
        assert lines[2].startswith("| min |")
        assert lines[3].startswith("| max |")

    def test_cluster_patch_level_ignored(self):
        """A real cluster version like 1.28.2 sits INSIDE the 1.28 max
        bracket: the compatibility check is minor-granular."""
        m = kompat.parse(MATRIX)
        assert m.compatible("0.31.0", "1.28.2")
        assert not m.compatible("0.31.0", "1.29.0")

    def test_unquoted_trailing_zero_versions_survive_yaml(self, tmp_path):
        """`maxK8sVersion: 1.30` unquoted must stay '1.30', not the float
        1.3 (BaseLoader keeps scalars strings, like the Go decoder)."""
        path = tmp_path / "m.yaml"
        path.write_text(
            "name: t\ncompatibility:\n"
            "  - appVersion: 0.30\n"
            "    minK8sVersion: 1.23\n"
            "    maxK8sVersion: 1.30\n"
        )
        m = kompat.load(str(path))
        assert m.rows[0].max_k8s == "1.30"
        assert m.compatible("0.30", "1.30")
        assert not m.compatible("0.30", "1.31")

    def test_cli_round_trip(self, tmp_path):
        import yaml

        path = tmp_path / "matrix.yaml"
        path.write_text(yaml.safe_dump(MATRIX))
        assert kompat.main([str(path), "--app-version", "0.31.0",
                            "--k8s-version", "1.27"]) == 0
        assert kompat.main([str(path), "--app-version", "0.30.0",
                            "--k8s-version", "1.28"]) == 1
        assert kompat.main([str(path), "-n", "2"]) == 0
        # unknown app version (e.g. trimmed by --last-n): diagnostic, rc 2
        assert kompat.main([str(path), "-n", "1", "--app-version", "0.30.0",
                            "--k8s-version", "1.27"]) == 2


class TestMetricsDocGen:
    def test_doc_matches_source(self):
        """docs/metrics.md is generated; regeneration must be a no-op
        (the reference's codegen-freshness contract, hack/codegen.sh)."""
        import pathlib

        from karpenter_tpu.tools.gen_metrics_doc import render

        pkg = pathlib.Path(__file__).resolve().parent.parent / "karpenter_tpu"
        doc = pkg.parent / "docs" / "metrics.md"
        assert doc.read_text() == render(pkg)

    def test_families_documented(self):
        import pathlib

        from karpenter_tpu.tools.gen_metrics_doc import collect

        pkg = pathlib.Path(__file__).resolve().parent.parent / "karpenter_tpu"
        assert len(collect(pkg)) >= 40
