"""Delta-correctness fuzz for the device-resident cluster tensors.

The resident layer (ops/resident.py) may change HOW the solve tensors are
built — scatter deltas on donated device buffers instead of a per-tick
re-tensorize — but never WHAT they contain.  This suite drives seeded
random mutation streams (pod arrive / delete / in-place mutate, node
add / remove / label flip / usage change, catalog roll) through the real
`TensorScheduler.solve` path and, after EVERY step, asserts the resident
state's tensors are bit-equal to a from-scratch `compile_problem` over
the same cluster — on the single-device backend AND the mesh-sharded one
(conftest pins an 8-device virtual CPU platform).

Three layers of equality per step:
  1. snapshot vs scratch: the `CompiledProblem` the solver consumed is
     bit-identical (every tensor, the class membership, the config list)
     to a fresh compile by an independent scheduler;
  2. device vs host: the device buffers mirror the host mirrors exactly
     (the donated jit replayed every host edit faithfully);
  3. pad hygiene: the padded regions still hold canonical pad values
     (price inf, feasibility False, cfg -1) — a scratch-slot leak would
     poison some LATER delta's gather, not this step's, so it must be
     pinned directly.
"""

import random

import numpy as np
import pytest

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm
from karpenter_tpu.ops.tensorize import partition_groups
from karpenter_tpu.parallel.mesh import make_mesh, mesh_pack_fn
from karpenter_tpu.scheduling.solver import TensorScheduler
from karpenter_tpu.state.cluster import StateNode
from karpenter_tpu.state.kube import Node
from karpenter_tpu.testing import Environment

ZONES = ("zone-a", "zone-b", "zone-c")

# plain resident-expressible pod shapes: distinct requests -> distinct
# classes, so arrivals/mutations move class boundaries around
SIZES = (
    Resources(cpu=0.25, memory="256Mi"),
    Resources(cpu=0.5, memory="512Mi"),
    Resources(cpu=1, memory="1Gi"),
    Resources(cpu=2, memory="2Gi"),
    Resources(cpu=2, memory="8Gi"),
    Resources(cpu=4, memory="4Gi"),
)

TENSORS = (
    "req", "cnt", "maxper", "slot", "feas", "alloc", "price",
    "openable", "used0", "cfg0", "npods0",
)


@pytest.fixture(scope="module")
def setup():
    env = Environment()
    pool = env.default_node_pool()
    nc = env.default_node_class()
    # a trimmed inventory keeps the catalog (and every jit bucket) small
    # enough that the per-step scratch compiles dominate the runtime, not
    # XLA compilation of one-off shapes
    types = env.instance_types.list(pool, nc)[:24]
    return pool, types


class _Fuzz:
    """One seeded mutation stream over one long-lived scheduler."""

    def __init__(self, pool, types, seed: int, pack_fn=None):
        self.pool = pool
        self.types = list(types)
        self.inventory = {pool.name: self.types}
        self.rng = random.Random(seed)
        self.pods: list = []
        self.live: list = []
        self.n_node = 0
        kw = {} if pack_fn is None else {"pack_fn": pack_fn}
        self.ts = TensorScheduler([pool], self.inventory, **kw)
        self.checked = 0
        self.skipped = 0

    # ------------------------------------------------------------- ops
    def _new_pod(self) -> Pod:
        p = Pod(requests=self.rng.choice(SIZES))
        if self.rng.random() < 0.25:
            # a single-term selector: a different constraint signature
            # (and feasibility row) without leaving the plain shape
            p.node_selector = {L.LABEL_ZONE: self.rng.choice(ZONES)}
        return p

    def op_arrive(self):
        for _ in range(self.rng.randint(1, 5)):
            self.pods.append(self._new_pod())

    def op_delete(self):
        for _ in range(min(self.rng.randint(1, 3), len(self.pods))):
            self.pods.pop(self.rng.randrange(len(self.pods)))

    def op_mutate_pod(self):
        if not self.pods:
            return
        p = self.rng.choice(self.pods)
        # in-place requests change: bumps _mut, moves p to another class
        p.requests = self.rng.choice(SIZES)

    def op_add_node(self):
        self.n_node += 1
        self.live.append(
            StateNode(
                name=f"fz-{self.n_node}",
                provider_id=f"fake://fz-{self.n_node}",
                labels={
                    L.LABEL_ZONE: self.rng.choice(ZONES),
                    L.LABEL_NODEPOOL: self.pool.name,
                },
                taints=[],
                allocatable=Resources(cpu=16, memory="64Gi", pods=110),
                pods=[],
                used=Resources(),
            )
        )

    def op_remove_node(self):
        if self.live:
            self.live.pop(self.rng.randrange(len(self.live)))

    def op_mutate_node_labels(self):
        if not self.live:
            return
        sn = self.rng.choice(self.live)
        # flip the zone label in place: the scheduling fingerprint
        # changes, so the node's feasibility column must re-scatter
        sn.labels[L.LABEL_ZONE] = self.rng.choice(ZONES)

    def op_mutate_node_usage(self):
        if not self.live:
            return
        sn = self.rng.choice(self.live)
        if sn.pods and self.rng.random() < 0.4:
            bp = sn.pods.pop()
            sn.used = (sn.used - bp.requests).clamp_nonnegative()
        else:
            bp = Pod(requests=self.rng.choice(SIZES))
            sn.pods.append(bp)
            sn.used = sn.used + bp.requests

    def op_catalog_roll(self):
        # new instance-type list object == a provider refresh: every
        # identity-keyed cache (catalog, compile cache, resident state)
        # must miss and rebuild
        self.types = list(self.types)
        self.inventory = {self.pool.name: self.types}

    OPS = (
        ("arrive", 5),
        ("delete", 2),
        ("mutate_pod", 2),
        ("add_node", 3),
        ("remove_node", 1),
        ("mutate_node_labels", 1),
        ("mutate_node_usage", 2),
    )

    def step(self, roll: bool = False):
        if roll:
            self.op_catalog_roll()
        else:
            names = [n for n, w in self.OPS for _ in range(w)]
            for _ in range(self.rng.randint(1, 3)):
                getattr(self, f"op_{self.rng.choice(names)}")()
        # keep the batch inside one padded-bucket neighborhood so the
        # run exercises deltas, not only bucket-overflow rebuilds
        while len(self.pods) > 48:
            self.pods.pop(self.rng.randrange(len(self.pods)))
        if not self.pods:
            self.op_arrive()
        self.ts.update(
            [self.pool], self.inventory, existing=list(self.live)
        )
        self.ts.solve(list(self.pods))
        self.check()

    # ---------------------------------------------------------- checks
    def _state(self):
        """The state that served THIS solve: refresh moves the absorbing
        state to the MRU slot and rebuild appends, so it is always the
        last one (a stale LRU sibling may coincidentally hold the same
        pod-id set — never trust membership alone)."""
        if not self.ts.last_resident:
            return None
        st = self.ts._resident.states[-1]
        assert set(st.pod_entry) == set(map(id, self.pods))
        return st

    def _scratch(self):
        ref = TensorScheduler([self.pool], dict(self.inventory))
        ref.update(
            [self.pool], self.inventory, existing=list(self.live)
        )
        sup, unsup, _why = partition_groups(
            self.pods, existing=ref.existing, pools=ref.pools
        )
        assert not unsup, "fuzz batches must stay resident-expressible"
        return ref._compile_tensor(
            [p for _, members in sup for p in members], sup
        )

    def check(self):
        st = self._state()
        if st is None:
            # a compile-cache hit can re-serve a prior snapshot whose
            # state has since moved on (`match` finds nothing): the
            # tensors it served were scratch-checked when stored — count
            # it, the stream-level floor below keeps it rare
            self.skipped += 1
            return
        self.checked += 1
        ref = self._scratch()
        prob = st.problem()
        # 1) snapshot vs scratch: every tensor bit-equal
        assert prob.axes == ref.axes
        for name in TENSORS:
            np.testing.assert_array_equal(
                getattr(prob, name), getattr(ref, name), err_msg=name
            )
        # identical class membership (same pod OBJECTS, same order)
        assert [
            sorted(map(id, cm.pods)) for cm in prob.classes
        ] == [sorted(map(id, cm.pods)) for cm in ref.classes]
        # identical config identity row-for-row
        assert [
            (c.zone, c.capacity_type, c.price,
             c.existing.name if c.existing is not None else None)
            for c in prob.configs
        ] == [
            (c.zone, c.capacity_type, c.price,
             c.existing.name if c.existing is not None else None)
            for c in ref.configs
        ]
        # 2) device buffers mirror the host mirrors exactly
        for d, h in (
            (st.d_req, st.h_req), (st.d_cnt, st.h_cnt),
            (st.d_feas, st.h_feas), (st.d_alloc, st.h_alloc),
            (st.d_price, st.h_price), (st.d_used0, st.h_used0),
            (st.d_npods0, st.h_npods0),
        ):
            np.testing.assert_array_equal(np.asarray(d), h)
        E = len(st.live)
        cfg0 = np.asarray(st.d_cfg0)
        assert (cfg0[:E] == np.arange(st.fe, st.fe + E)).all()
        assert (cfg0[E:] == -1).all()
        # 3) pad hygiene: the scratch slots and padded tails still hold
        # canonical pad values (a leak would poison a LATER gather)
        G, C = len(st.cls), st.fe + E
        assert not st.h_feas[G:, :].any() and not st.h_feas[:, C:].any()
        assert (st.h_req[G:] == 0).all() and (st.h_cnt[G:] == 0).all()
        assert np.isinf(st.h_price[C:]).all()
        assert (st.h_alloc[C:] == 0).all()
        assert (st.h_used0[E:] == 0).all() and (st.h_npods0[E:] == 0).all()


def _run(pool, types, seed, pack_fn=None, steps=25, rolls=(9, 17)):
    fz = _Fuzz(pool, types, seed, pack_fn=pack_fn)
    for i in range(steps):
        fz.step(roll=i in rolls)
    ts = fz.ts
    # the stream must have actually exercised the warm path AND the
    # rebuild fallback (cold start + the catalog rolls at minimum)
    assert fz.checked >= steps - max(2, steps // 8), (
        fz.checked, fz.skipped,
    )
    assert ts.resident_rebuilds >= 1 + len(rolls), (
        ts.resident_hits, ts.resident_rebuilds,
    )
    assert ts.resident_hits > ts.resident_rebuilds, (
        ts.resident_hits, ts.resident_rebuilds,
    )
    return ts


def _fresh_solve(pool, inventory, existing, pods):
    ref = TensorScheduler([pool], dict(inventory))
    ref.update([pool], inventory, existing=list(existing))
    return ref.solve(list(pods))


def _placement_key(result):
    return (
        sorted(
            (len(n.pods), n.feasible_types[0].name) for n in result.new_nodes
        ),
        sorted(result.unschedulable),
    )


def _carrier_node(pool, name="anti-1"):
    """A CORDONED node whose bound pod carries an everything-matching
    anti-affinity term: invisible to the live filter, but partition_
    groups still repels every batch class it selects."""
    carrier = Pod(
        labels={"app": "repel"},
        requests=Resources(cpu=0.5),
        pod_affinity=[
            PodAffinityTerm(
                topology_key=L.LABEL_ZONE, label_selector=(), anti=True
            )
        ],
    )
    return StateNode(
        name=name,
        provider_id=f"fake://{name}",
        labels={L.LABEL_ZONE: ZONES[0], L.LABEL_NODEPOOL: pool.name},
        taints=[],
        allocatable=Resources(cpu=16, memory="64Gi", pods=110),
        pods=[carrier],
        used=Resources(cpu=0.5),
        node=Node(name=name, cordoned=True),
    )


class TestResidentCoherence:
    """Pinned regressions for state the resident layer shares with the
    solver's compile cache and for non-live carriers the live filter
    hides."""

    def _ts(self, pool, types, existing=()):
        inventory = {pool.name: list(types)}
        ts = TensorScheduler([pool], inventory)
        ts.update([pool], inventory, existing=list(existing))
        return ts, inventory

    def test_cache_hit_after_delta_decodes_original_membership(self, setup):
        """Reverting to an earlier batch re-serves the earlier cache
        entry, which must be UNCHANGED: tick 2's delta may not mutate
        the ClassMeta objects tick 1's cached problem shares, or the
        cached cnt desyncs from its membership and decode conjures a
        pod that is not in the batch."""
        pool, types = setup
        ts, _ = self._ts(pool, types)
        a = Pod(requests=SIZES[2])
        b = Pod(requests=SIZES[2])  # same class as a
        ts.solve([a])
        assert ts.last_path == "tensor"
        ts.solve([a, b])
        assert ts.last_resident  # the arrival rode the delta path
        res = ts.solve([a])  # revert: same object+epoch -> cache hit
        assert ts.compile_cache_hits >= 1
        assert not res.unschedulable  # no phantom 'b' from the entry
        assert [id(p) for n in res.new_nodes for p in n.pods] == [id(a)]

    def test_equal_count_swap_refreshes_snapshot(self, setup):
        """b -> c inside one class keeps every tensor bit-identical
        (zero delta rows) but decode must assign the CURRENT pod
        objects — the snapshot refreshes on membership change alone."""
        pool, types = setup
        ts, _ = self._ts(pool, types)
        a, b, c = (Pod(requests=SIZES[1]) for _ in range(3))
        ts.solve([a, b])
        res = ts.solve([a, c])
        assert ts.last_resident
        assert not res.unschedulable
        assert {id(p) for n in res.new_nodes for p in n.pods} == {
            id(a), id(c),
        }

    def test_cordoned_carrier_blocks_resident_seed(self, setup):
        """With an anti-affinity carrier on a cordoned node, the batch is
        oracle-routed (symmetric repel) and the resident layer must stay
        out entirely."""
        pool, types = setup
        ts, inventory = self._ts(pool, types, existing=[_carrier_node(pool)])
        pods = [Pod(requests=SIZES[0]) for _ in range(6)]
        res1 = ts.solve(list(pods))
        res2 = ts.solve(list(pods))
        assert ts.resident_hits == 0 and not ts.last_resident
        ref = _fresh_solve(pool, inventory, [_carrier_node(pool)], pods)
        assert _placement_key(res1) == _placement_key(ref)
        assert _placement_key(res2) == _placement_key(ref)

    def test_carrier_arrival_falls_back_to_full_compile(self, setup):
        """A carrier appearing on a NON-live node mid-stream (cordon of a
        carrier node, here: a cordoned arrival) must force the warm path
        back to the full compile — partition_groups repels batch pods
        the delta planner has no way to see."""
        pool, types = setup
        ts, inventory = self._ts(pool, types)
        pods = [Pod(requests=SIZES[0]) for _ in range(6)]
        ts.solve(list(pods))
        pods.append(Pod(requests=SIZES[0]))
        ts.solve(list(pods))
        assert ts.last_resident  # warm path proven before the carrier
        cordoned = _carrier_node(pool)
        ts.update([pool], inventory, existing=[cordoned])
        pods.append(Pod(requests=SIZES[0]))
        res = ts.solve(list(pods))
        assert not ts.last_resident
        ref = _fresh_solve(pool, inventory, [cordoned], pods)
        assert _placement_key(res) == _placement_key(ref)


class TestResidentFuzz:
    @pytest.mark.parametrize("seed", [7, 23])
    def test_single_device_stream(self, setup, seed):
        pool, types = setup
        _run(pool, types, seed)

    def test_mesh_stream(self, setup):
        """The same contract with the buffers sharded over the 8-device
        mesh (class/config axes on "model", node slots on "data") — the
        resident path is the same code single-device and multi-chip."""
        pool, types = setup
        _run(
            pool, types, 101,
            pack_fn=mesh_pack_fn(make_mesh(8)), steps=16, rolls=(7,),
        )

    def test_decode_parity_with_scratch_solver(self, setup):
        """End-to-end: a churned resident scheduler and a fresh scheduler
        solving the same cluster must place identically (node counts and
        per-node pod-count/type multisets)."""
        pool, types = setup
        fz = _Fuzz(pool, types, 77)
        for i in range(10):
            fz.step(roll=(i == 5))
        res = fz.ts.solve(list(fz.pods))
        fresh = TensorScheduler([pool], fz.inventory)
        fresh.update([pool], fz.inventory, existing=list(fz.live))
        ref = fresh.solve(list(fz.pods))
        assert fz.ts.last_resident
        assert len(res.new_nodes) == len(ref.new_nodes)
        key = lambda n: (len(n.pods), n.feasible_types[0].name)
        assert sorted(map(key, res.new_nodes)) == sorted(
            map(key, ref.new_nodes)
        )
        assert res.unschedulable == ref.unschedulable
