"""Multi-node consolidation subset search (round-2 VERDICT item #6): the
drop-one refinement must find profitable candidate sets that are
NON-CONTIGUOUS in disruption-cost order — a prefix-only scan cannot
(designs/consolidation.md:23-40)."""

import pytest

from karpenter_tpu.api import (
    Disruption,
    NodeClaim,
    Pod,
    Requirement,
    Requirements,
    Resources,
)
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.state.kube import Node
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    return Environment()


def _make_node(env, name, zone, cpu, price, pods):
    """A registered+initialized claim/node pair with bound pods."""
    claim = NodeClaim(
        name=name,
        pool_name="default",
        node_class_ref="default",
        provider_id=f"i-{name}",
        zone=zone,
        capacity_type=L.CAPACITY_TYPE_ON_DEMAND,
        price=price,
        capacity=Resources(cpu=cpu, memory=f"{cpu * 4}Gi", pods=110),
        allocatable=Resources(cpu=cpu, memory=f"{cpu * 4}Gi", pods=110),
        labels={
            L.LABEL_NODEPOOL: "default",
            L.LABEL_ZONE: zone,
            L.LABEL_CAPACITY_TYPE: L.CAPACITY_TYPE_ON_DEMAND,
        },
        created_at=env.clock.now(),
    )
    claim.set_condition("Launched")
    claim.set_condition("Registered")
    claim.set_condition("Initialized")
    env.kube.put_node_claim(claim)
    env.kube.put_node(
        Node(
            name=name,
            provider_id=claim.provider_id,
            labels=dict(claim.labels),
            taints=[],
            capacity=claim.capacity,
            allocatable=claim.allocatable,
            ready=True,
            created_at=env.clock.now(),
        )
    )
    for p in pods:
        env.kube.put_pod(p)
        env.kube.bind_pod(p.key(), name)
    return claim


def test_non_contiguous_subset_beats_every_prefix(env):
    env.default_node_class()
    env.default_node_pool(
        requirements=Requirements(
            [Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])]
        ),
        disruption=Disruption(consolidation_policy="WhenUnderutilized"),
    )
    # cost ranking is by pod count first, so the order is c0 < c1 < c2.
    # c1 (the MIDDLE candidate) is poisoned: its pods require zone-b,
    # which the pool cannot launch into — any subset containing c1 fails
    # the simulation, so every prefix of size >= 2 fails, while {c0, c2}
    # consolidates onto one cheap zone-a replacement.
    c0 = _make_node(
        env, "c0", "zone-a", 8, 0.40,
        [
            Pod(
                requests=Resources(cpu=1, memory="1Gi"),
                node_selector={L.LABEL_ZONE: "zone-a"},
            )
        ],
    )
    c1 = _make_node(
        env, "c1", "zone-b", 32, 0.90,
        [
            Pod(
                requests=Resources(cpu=14, memory="24Gi"),
                node_selector={L.LABEL_ZONE: "zone-b"},
            )
            for _ in range(2)
        ],
    )
    c2 = _make_node(
        env, "c2", "zone-a", 8, 0.45,
        [
            Pod(
                requests=Resources(cpu=1, memory="1Gi"),
                node_selector={L.LABEL_ZONE: "zone-a"},
            )
            for _ in range(3)
        ],
    )
    dc = env.operator.disruption
    dc._budgets = dc._remaining_budgets()
    ranked = sorted(
        (c for c in dc._candidates() if dc._consolidatable(c)),
        key=lambda c: c.disruption_cost(),
    )
    assert [c.claim.name for c in ranked] == ["c0", "c1", "c2"]
    # prefixes containing c1 are infeasible, the non-contiguous pair works
    assert not dc._simulate([ranked[0], ranked[1], ranked[2]])[0]
    assert not dc._simulate([ranked[0], ranked[1]])[0]
    fits, rep_price, _ = dc._simulate([ranked[0], ranked[2]])
    assert fits and 0 < rep_price < c0.price + c2.price

    assert dc._consolidate_multi(ranked)
    # the action pre-spun ONE replacement covering exactly {c0, c2}
    (pending,) = dc._pending.values()
    assert sorted(pending.candidate_names) == ["c0", "c2"]


def test_small_prefix_found_after_descent_budget_exhausts(env):
    """With a full pool of 10 candidates where every subset containing a
    poisoned node is infeasible, the drop-one descent burns its simulation
    budget at large sizes; the memoized prefix-scan floor must still find
    the feasible {c0, c1} pair the old prefix scan guaranteed."""
    env.default_node_class()
    env.default_node_pool(
        requirements=Requirements(
            [Requirement(L.LABEL_ZONE, Op.IN, ["zone-a"])]
        ),
        disruption=Disruption(consolidation_policy="WhenUnderutilized"),
    )
    for i, name in enumerate(["c0", "c1"]):
        _make_node(
            env, name, "zone-a", 8, 0.40 + i * 0.01,
            [
                Pod(
                    requests=Resources(cpu=1, memory="1Gi"),
                    node_selector={L.LABEL_ZONE: "zone-a"},
                )
            ],
        )
    for i in range(8):
        _make_node(
            env, f"p{i}", "zone-b", 32, 0.90,
            [
                Pod(
                    requests=Resources(cpu=14, memory="24Gi"),
                    node_selector={L.LABEL_ZONE: "zone-b"},
                )
                for _ in range(2)
            ],
        )
    dc = env.operator.disruption
    dc._budgets = dc._remaining_budgets()
    ranked = sorted(
        (c for c in dc._candidates() if dc._consolidatable(c)),
        key=lambda c: c.disruption_cost(),
    )
    assert len(ranked) == 10
    assert {c.claim.name for c in ranked[:2]} == {"c0", "c1"}
    assert dc._consolidate_multi(ranked)
    (pending,) = dc._pending.values()
    assert sorted(pending.candidate_names) == ["c0", "c1"]


def test_prefix_still_wins_when_it_is_best(env):
    """Sanity: when the full top set consolidates, the search takes it
    in one simulation (no behavior regression vs the prefix scan)."""
    env.default_node_class()
    env.default_node_pool(
        disruption=Disruption(consolidation_policy="WhenUnderutilized")
    )
    for i in range(3):
        _make_node(
            env, f"n{i}", "zone-a", 8, 0.40,
            [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(i + 1)],
        )
    dc = env.operator.disruption
    dc._budgets = dc._remaining_budgets()
    ranked = sorted(
        (c for c in dc._candidates() if dc._consolidatable(c)),
        key=lambda c: c.disruption_cost(),
    )
    assert dc._consolidate_multi(ranked)
    (pending,) = dc._pending.values()
    assert sorted(pending.candidate_names) == ["n0", "n1", "n2"]
