"""Randomized tensor-vs-oracle parity fuzzing (battletest analogue).

The reference's `make battletest` re-runs suites with randomized spec
order to shake out order dependence (Makefile:73-80).  The solver's
equivalent risk surface is WORKLOAD shape: the partition/merge logic
(ops/tensorize.py:partition_groups) routes each constraint mix to the
tensor kernel, a macro merge, or the oracle continuation, and a routing
bug shows up as a semantics violation, not a crash.  Each seed below
generates a mixed workload and asserts the INVARIANTS every path must
preserve:

- every pod placed or reported unschedulable (none dropped)
- required hostname co-location groups land on one node
- hostname anti-affinity singletons never share a node
- DoNotSchedule zone spread skew within max_skew over the placed set
- tolerations: no pod on a pool whose taints it doesn't tolerate
- node count within 1.3x + 1 of the pure-oracle pack (quality bound,
  loose enough for the hybrid right-sizing artifact)
"""

import random

import pytest

from karpenter_tpu.api import Pod, Requirement, Resources, Taint, Toleration
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.api.requirements import Op
from karpenter_tpu.scheduling import Scheduler, TensorScheduler
from karpenter_tpu.testing import Environment


@pytest.fixture(scope="module")
def setup():
    env = Environment()
    nc = env.default_node_class()
    general = env.default_node_pool(name="general")
    tainted = env.default_node_pool(
        name="tainted",
        taints=[Taint(key="team", value="ml", effect="NoSchedule")],
    )
    # racked pools: domains for CUSTOM-topology-key spreads (each pool
    # single-valued for the key, so the compiled split partitions pools)
    rack_a = env.default_node_pool(
        name="rack-a", labels={"example.com/rack": "r1"}
    )
    rack_b = env.default_node_pool(
        name="rack-b", labels={"example.com/rack": "r2"}
    )
    pools = [general, tainted, rack_a, rack_b]
    inventory = {p.name: env.instance_types.list(p, nc) for p in pools}
    return pools, inventory


SIZES = [
    Resources(cpu=0.25, memory="512Mi"),
    Resources(cpu=1, memory="2Gi"),
    Resources(cpu=2, memory="4Gi"),
    Resources(cpu=4, memory="8Gi"),
]


def _workload(rng: random.Random):
    pods = []
    for i in range(rng.randint(40, 120)):
        pods.append(Pod(requests=rng.choice(SIZES)))
    # preference carriers: some satisfiable (a real zone), some requiring
    # relaxation (an impossible zone)
    for i in range(rng.randint(0, 15)):
        zone = rng.choice(["zone-a", "zone-b", "zone-nowhere"])
        pods.append(
            Pod(
                requests=rng.choice(SIZES[:3]),
                preferred_affinity=[Requirement(L.LABEL_ZONE, Op.IN, [zone])],
            )
        )
    # OR-term carriers: first term sometimes impossible, second real
    for i in range(rng.randint(0, 10)):
        first = rng.choice(["zone-a", "zone-nowhere"])
        pods.append(
            Pod(
                requests=rng.choice(SIZES[:3]),
                affinity_terms=[
                    (Requirement(L.LABEL_ZONE, Op.IN, [first]),),
                    (Requirement(L.LABEL_ZONE, Op.IN, ["zone-b"]),),
                ],
            )
        )
    # tainted-pool pods
    for i in range(rng.randint(0, 20)):
        pods.append(
            Pod(
                requests=rng.choice(SIZES),
                tolerations=[Toleration(key="team", value="ml", effect="NoSchedule")],
                node_selector={L.LABEL_NODEPOOL: "tainted"},
            )
        )
    # spread services; some span label variants (cross-class mutual
    # spread, compiled via the shared split accumulator)
    for s in range(rng.randint(0, 3)):
        sel = (("svc", f"s{s}"),)
        c = TopologySpreadConstraint(
            max_skew=rng.choice([1, 2]),
            topology_key=L.LABEL_ZONE,
            label_selector=sel,
        )
        cross = rng.random() < 0.5
        for i in range(rng.randint(3, 30)):
            labels = {"svc": f"s{s}"}
            if cross:
                labels["variant"] = str(i % 2)
            pods.append(
                Pod(
                    labels=labels,
                    requests=rng.choice(SIZES[:3]),
                    topology_spread=[c],
                )
            )
    # co-location groups: self-selecting, node-equivalent cross-class, and
    # node-INEQUIVALENT cross-class (oracle) variants
    for g in range(rng.randint(0, 4)):
        kind = rng.choice(["self", "cross", "oracle"])
        term = PodAffinityTerm(
            topology_key=L.LABEL_HOSTNAME,
            label_selector=(("pair", f"g{g}"),),
        )
        for i in range(rng.randint(2, 5)):
            labels = {"pair": f"g{g}"}
            kw = {}
            if kind in ("cross", "oracle"):
                labels["variant"] = str(i % 2)
            if kind == "oracle" and i % 2:
                kw["tolerations"] = [
                    Toleration(key="burst", value="y", effect="NoSchedule")
                ]
            pods.append(
                Pod(
                    labels=labels,
                    requests=rng.choice(SIZES[:2]),
                    pod_affinity=[term],
                    **kw,
                )
            )
    # custom-topology-key spread service (compiled via the pool-template
    # domain partition; scheduling.md:319-331)
    if rng.random() < 0.5:
        c = TopologySpreadConstraint(
            max_skew=rng.choice([1, 2]),
            topology_key="example.com/rack",
            label_selector=(("svc", "racked"),),
        )
        for i in range(rng.randint(2, 12)):
            pods.append(
                Pod(
                    labels={"svc": "racked"},
                    requests=rng.choice(SIZES[:3]),
                    topology_spread=[c],
                )
            )
    # anti-affinity singletons; sometimes cross-class (variant labels
    # under one selector, compiled via the shared tracking slot)
    anti_cross = rng.random() < 0.5
    for i in range(rng.randint(0, 12)):
        labels = {"app": "solo"}
        if anti_cross:
            labels["variant"] = str(i % 2)
        pods.append(
            Pod(
                labels=labels,
                requests=SIZES[0],
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=(("app", "solo"),),
                        anti=True,
                    )
                ],
            )
        )
    rng.shuffle(pods)
    return pods


def _placements(result):
    """pod-key -> (node name, node object|None) over new nodes."""
    out = {}
    for vn in result.new_nodes:
        for p in vn.pods:
            out[p.key()] = (vn.name, vn)
    return out


@pytest.mark.parametrize("seed", range(12))
def test_random_workload_invariants(setup, seed):
    pools, inventory = setup
    rng = random.Random(seed)
    pods = _workload(rng)
    ts = TensorScheduler(pools, inventory)
    res = ts.solve(pods)
    oracle = Scheduler(pools, inventory).solve(pods)

    placed = _placements(res)
    # 1. conservation: every pod placed or reported unschedulable
    assert len(placed) + len(res.existing_placements) + len(res.unschedulable) == len(pods)

    # 2. co-location: placed members of one group share a node
    groups = {}
    for p in pods:
        if p.pod_affinity and not p.pod_affinity[0].anti and "pair" in p.labels:
            if p.key() in placed:
                groups.setdefault(p.labels["pair"], set()).add(placed[p.key()][0])
    for gname, nodes in groups.items():
        assert len(nodes) == 1, (seed, gname, nodes)

    # 3. anti-affinity singletons never share a node
    solo_nodes = [
        placed[p.key()][0]
        for p in pods
        if p.pod_affinity and p.pod_affinity[0].anti and p.key() in placed
    ]
    assert len(solo_nodes) == len(set(solo_nodes)), seed

    # 4. zone spread within skew (over fully-placed services)
    for s in range(4):
        svc = [p for p in pods if p.labels.get("svc") == f"s{s}"]
        if not svc or any(p.key() in res.unschedulable for p in svc):
            continue
        skew = svc[0].topology_spread[0].max_skew
        counts = {}
        for p in svc:
            name, vn = placed[p.key()]
            opts = vn.zone_options()
            assert opts, (seed, name)
            # the committed zone is pinned for spread-constrained pods
            zone = min(opts)
            counts[zone] = counts.get(zone, 0) + 1
        if len(counts) > 1:
            assert max(counts.values()) - min(counts.values()) <= skew, (
                seed,
                counts,
            )

    # 4b. custom-key (rack) spread within skew over the placed set
    racked = [p for p in pods if p.labels.get("svc") == "racked"]
    if racked and not any(p.key() in res.unschedulable for p in racked):
        skew = racked[0].topology_spread[0].max_skew
        counts = {}
        for p in racked:
            name, vn = placed[p.key()]
            rack = vn.requirements.get("example.com/rack")
            assert rack is not None, (seed, name)
            counts[rack.any_value()] = counts.get(rack.any_value(), 0) + 1
        if len(counts) > 1:
            assert max(counts.values()) - min(counts.values()) <= skew, (
                seed,
                counts,
            )

    # 5. taints honored
    for p in pods:
        if p.key() in placed:
            _, vn = placed[p.key()]
            from karpenter_tpu.api.objects import tolerates_all

            assert tolerates_all(p.tolerations, vn.pool.taints), seed

    # 6. quality: within 30% + 1 node of the oracle pack
    assert res.node_count() <= oracle.node_count() * 1.3 + 1, (
        seed,
        res.node_count(),
        oracle.node_count(),
    )

    # 7. the oracle, as semantics definition, must also place everything
    #    the tensor path placed (sanity on the generator, not the solver)
    assert len(res.unschedulable) <= len(oracle.unschedulable) + 2, seed


def _existing_cluster(rng: random.Random):
    """Random live nodes, some holding bound pods that incoming selectors
    can reach (anti-affinity blocks, co-location live-members -> oracle)."""
    from karpenter_tpu.state.cluster import StateNode

    nodes = []
    for n in range(rng.randint(2, 6)):
        cap = rng.choice([8, 16, 32])
        bound = []
        used = Resources()
        for b in range(rng.randint(0, 3)):
            labels = {}
            kw = {}
            r = rng.random()
            if r < 0.2:
                labels = {"app": "solo"}  # blocks anti-affinity singletons
            elif r < 0.3:
                labels = {"pair": "g0"}  # live co-location member
            elif r < 0.4:
                # live ANTI CARRIER: symmetric anti-affinity repels
                # incoming matched pods from this node; selected incoming
                # classes route to the oracle (partition live_anti)
                kw["pod_affinity"] = [
                    PodAffinityTerm(
                        topology_key=L.LABEL_HOSTNAME,
                        label_selector=(("app", "solo"),),
                        anti=True,
                    )
                ]
            p = Pod(labels=labels, requests=Resources(cpu=1, memory="2Gi"), **kw)
            bound.append(p)
            used = used + p.requests
        nodes.append(
            StateNode(
                name=f"live-{n}",
                provider_id=f"fake://live-{n}",
                labels={
                    L.LABEL_ZONE: rng.choice(["zone-a", "zone-b", "zone-c"]),
                    L.LABEL_NODEPOOL: "general",
                },
                taints=[],
                allocatable=Resources(cpu=cap, memory=f"{cap * 4}Gi", pods=110),
                pods=bound,
                used=used,
            )
        )
    return nodes


@pytest.mark.parametrize("seed", range(8))
def test_random_workload_with_existing_nodes(setup, seed):
    """Continuation over a live cluster: capacity on existing nodes is
    respected, anti-affinity sees bound pods, and nothing is dropped."""
    pools, inventory = setup
    rng = random.Random(1000 + seed)
    existing = _existing_cluster(rng)
    pods = _workload(rng)
    ts = TensorScheduler(pools, inventory, existing=existing)
    res = ts.solve(pods)

    placed = _placements(res)
    assert len(placed) + len(res.existing_placements) + len(res.unschedulable) == len(pods)

    by_key = {p.key(): p for p in pods}
    # existing-node capacity: bound + newly placed fits allocatable
    for en in existing:
        add = Resources()
        for key, name in res.existing_placements.items():
            if name == en.name:
                add = add + by_key[key].requests
        total = en.used + add
        assert total.fits(en.allocatable), (seed, en.name)

    # anti-affinity: a live node holding an app=solo pod never receives a
    # solo singleton, and no two singletons share any node; a live ANTI
    # CARRIER's node never receives a pod its selector matches (symmetric
    # anti-affinity)
    solo_on = {}
    for key, name in res.existing_placements.items():
        p = by_key[key]
        if p.pod_affinity and p.pod_affinity[0].anti:
            solo_on.setdefault(name, []).append(key)
            en = next(e for e in existing if e.name == name)
            assert not any(
                bp.labels.get("app") == "solo" for bp in en.pods
            ), (seed, name)
        if p.labels.get("app") == "solo":
            en = next(e for e in existing if e.name == name)
            assert not any(
                t.anti and t.selects(p)
                for bp in en.pods
                for t in bp.pod_affinity
            ), (seed, name, "landed beside a live anti carrier")
    for name, keys in solo_on.items():
        assert len(keys) == 1, (seed, name)
    solo_new = [
        placed[p.key()][0]
        for p in pods
        if p.pod_affinity and p.pod_affinity[0].anti and p.key() in placed
    ]
    assert len(solo_new) == len(set(solo_new)), seed

    # co-location groups stay whole (one node, new or existing)
    groups = {}
    for p in pods:
        if p.pod_affinity and not p.pod_affinity[0].anti and "pair" in p.labels:
            k = p.key()
            if k in placed:
                groups.setdefault(p.labels["pair"], set()).add(placed[k][0])
            elif k in res.existing_placements:
                groups.setdefault(p.labels["pair"], set()).add(
                    res.existing_placements[k]
                )
    for gname, where in groups.items():
        assert len(where) == 1, (seed, gname, where)
