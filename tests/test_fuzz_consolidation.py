"""Randomized end-to-end consolidation convergence (battletest analogue
for the deprovisioning half).

Each seed builds a random cluster through the real provision path, deletes
a random fraction of the pods, then lets the full controller loop
(expiration/drift/emptiness/consolidation + termination + lifecycle) run
with the clock stepping forward.  Invariants:

- the cluster quiesces with no pending pods
- live node count never ends above the scale-up count, and shrinks when
  most pods were removed
- every bound pod's node exists, is live, and is not overcommitted
- no pod is lost (bound + pending == stored)
"""

import random

import pytest

from karpenter_tpu.api import Disruption, Pod, Resources
from karpenter_tpu.testing import Environment

SIZES = [
    Resources(cpu=0.25, memory="512Mi"),
    Resources(cpu=1, memory="2Gi"),
    Resources(cpu=2, memory="4Gi"),
    Resources(cpu=4, memory="8Gi"),
]


def _live_nodes(env):
    return [n for n in env.kube.nodes.values() if not n.deleted_at]


@pytest.mark.parametrize("seed", range(6))
def test_random_cluster_consolidation_convergence(seed):
    env = Environment()
    env.default_node_class()
    env.default_node_pool(
        disruption=Disruption(consolidation_policy="WhenUnderutilized")
    )
    rng = random.Random(seed)
    pods = [Pod(requests=rng.choice(SIZES)) for _ in range(rng.randint(80, 220))]
    for p in pods:
        env.kube.put_pod(p)
    env.settle(max_rounds=40)
    assert not env.kube.pending_pods(), seed
    n0 = len(_live_nodes(env))
    assert n0 > 0

    # remove a random 40-70% of the workload
    keys = list(env.kube.pods.keys())
    drop = rng.sample(keys, int(len(keys) * rng.uniform(0.4, 0.7)))
    for key in drop:
        env.kube.delete_pod(key)

    # run the controllers forward; consolidation pre-spins replacements,
    # drains, and terminates — give it wall-clock to do so
    for _ in range(40):
        env.clock.step(65)
        env.step(2.0)
    env.settle(max_rounds=20)

    assert not env.kube.pending_pods(), seed
    live = _live_nodes(env)
    assert len(live) <= n0, (seed, len(live), n0)
    if len(drop) >= len(keys) * 0.5 and n0 > 3:
        # most of the load left; the fleet must have shrunk
        assert len(live) < n0, (seed, len(live), n0)

    # conservation + capacity sanity
    node_names = {n.name for n in live}
    used = {}
    for p in env.kube.pods.values():
        if p.node_name:
            assert p.node_name in node_names, (seed, p.node_name)
            used[p.node_name] = used.get(p.node_name, Resources()) + p.requests
    for n in live:
        if n.name in used:
            assert used[n.name].fits(n.allocatable), (seed, n.name)


@pytest.mark.parametrize("seed", range(4))
def test_constrained_cluster_consolidation_invariants(seed):
    """Repacks must preserve co-location, anti-affinity, and volume zone
    pins while shrinking the fleet."""
    from karpenter_tpu.api import (
        PersistentVolumeClaim,
        Requirement,
        StorageClass,
    )
    from karpenter_tpu.api import labels as L
    from karpenter_tpu.api.objects import PodAffinityTerm

    env = Environment()
    env.default_node_class()
    env.default_node_pool(
        disruption=Disruption(consolidation_policy="WhenUnderutilized")
    )
    env.kube.put_storage_class(
        StorageClass(name="zonal", zones=("zone-b",), binding_mode="Immediate")
    )
    env.kube.put_pvc(PersistentVolumeClaim(name="vol", storage_class="zonal"))
    rng = random.Random(3000 + seed)
    pods = [Pod(requests=rng.choice(SIZES)) for _ in range(rng.randint(60, 140))]
    # co-location group
    term = PodAffinityTerm(
        topology_key=L.LABEL_HOSTNAME, label_selector=(("pair", "g"),)
    )
    coloc = [
        Pod(labels={"pair": "g"}, requests=SIZES[1], pod_affinity=[term])
        for _ in range(3)
    ]
    # anti-affinity singletons
    solo = [
        Pod(
            labels={"app": "solo"},
            requests=SIZES[0],
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=L.LABEL_HOSTNAME,
                    label_selector=(("app", "solo"),),
                    anti=True,
                )
            ],
        )
        for _ in range(rng.randint(2, 5))
    ]
    vol_pod = Pod(requests=SIZES[1], volume_claims=["vol"])
    all_pods = pods + coloc + solo + [vol_pod]
    for p in all_pods:
        env.kube.put_pod(p)
    env.settle(max_rounds=50)
    assert not env.kube.pending_pods(), seed
    n0 = len(_live_nodes(env))

    def check_invariants(tag):
        bound = {p.key(): p for p in env.kube.pods.values() if p.node_name}
        coloc_nodes = {
            bound[p.key()].node_name for p in coloc if p.key() in bound
        }
        assert len(coloc_nodes) <= 1, (seed, tag, coloc_nodes)
        solo_nodes = [
            bound[p.key()].node_name for p in solo if p.key() in bound
        ]
        assert len(solo_nodes) == len(set(solo_nodes)), (seed, tag)
        if vol_pod.key() in bound:
            node = env.kube.nodes[bound[vol_pod.key()].node_name]
            assert node.labels[L.LABEL_ZONE] == "zone-b", (seed, tag)

    check_invariants("after scale-up")
    # shrink the plain load
    for p in rng.sample(pods, int(len(pods) * 0.6)):
        env.kube.delete_pod(p.key())
    for _ in range(40):
        env.clock.step(65)
        env.step(2.0)
    env.settle(max_rounds=20)
    assert not env.kube.pending_pods(), seed
    assert len(_live_nodes(env)) <= n0, seed
    check_invariants("after consolidation")
