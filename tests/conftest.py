"""Test bootstrap: force JAX onto an 8-device virtual CPU mesh so every
sharding/pjit path is exercised without TPU hardware (the driver separately
dry-runs the multichip path; bench.py runs on the real chip)."""

from karpenter_tpu.testing import pin_cpu_platform

pin_cpu_platform(8)
