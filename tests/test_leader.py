"""Leader election: two replicas over one store, one reconciler.

The reference ships two controller replicas behind controller-runtime
leader election (charts/karpenter/templates/deployment.yaml + core
operator); here the Lease lives in the KubeStore and the elector gates
Operator.reconcile_once (utils/leader.py).
"""

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.metrics.registry import Registry
from karpenter_tpu.operator import Operator
from karpenter_tpu.testing import FAST_BATCH_WINDOWS, Environment
from karpenter_tpu.utils.leader import LEASE_DURATION_S, LeaderElector


def _two_replicas():
    env = Environment()
    env.default_node_class()
    env.default_node_pool()
    a = LeaderElector(env.kube, env.clock, "replica-a")
    b = LeaderElector(env.kube, env.clock, "replica-b")
    env.operator.elector = a
    reg_b = Registry()
    op_b = Operator(
        env.cloud,
        env.kube,
        settings=env.settings,
        clock=env.clock,
        registry=reg_b,
        batch_windows=FAST_BATCH_WINDOWS,
        elector=b,
    )
    return env, a, b, op_b, reg_b


class TestLease:
    def test_acquire_renew_exclusion_expiry(self):
        env = Environment()
        now = env.clock.now()
        assert env.kube.try_acquire_lease("l", "a", now, 15.0)
        # renewal by the holder succeeds; a competitor is rejected
        assert env.kube.try_acquire_lease("l", "a", now + 5, 15.0)
        assert not env.kube.try_acquire_lease("l", "b", now + 10, 15.0)
        # after expiry (last renewal + duration) the competitor takes it
        assert env.kube.try_acquire_lease("l", "b", now + 5 + 15.1, 15.0)
        assert env.kube.leases["l"].holder == "b"

    def test_release_only_by_holder(self):
        env = Environment()
        now = env.clock.now()
        env.kube.try_acquire_lease("l", "a", now, 15.0)
        env.kube.release_lease("l", "b")  # non-holder: no-op
        assert env.kube.leases["l"].holder == "a"
        env.kube.release_lease("l", "a")
        assert env.kube.leases["l"].holder == ""
        assert env.kube.try_acquire_lease("l", "b", now + 0.1, 15.0)


class TestTwoReplicas:
    def test_only_leader_reconciles(self):
        env, a, b, op_b, reg_b = _two_replicas()
        env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="2Gi")))
        env.settle()  # replica A ticks: takes the lease, provisions
        assert a.leading
        claims_after_a = len(env.kube.node_claims)
        assert claims_after_a >= 1
        # replica B ticks while A holds the lease: it must not reconcile
        # (no controller counters) and must not double-launch
        op_b.reconcile_once()
        assert not b.leading
        assert len(env.kube.node_claims) == claims_after_a
        assert not reg_b.counters.get("karpenter_controller_reconcile_total")
        assert reg_b.gauges["karpenter_leader_election_leading"][
            (("identity", "replica-b"),)
        ] == 0.0

    def test_standby_takes_over_after_leader_crash(self):
        env, a, b, op_b, reg_b = _two_replicas()
        env.step()  # A leads
        assert a.leading
        # A crashes: it stops renewing.  Within the lease duration the
        # standby still defers...
        env.clock.step(LEASE_DURATION_S / 2)
        op_b.reconcile_once()
        assert not b.leading
        # ...and past the expiry it takes over and serves new work (the
        # kubelet binds against the new leader's nominations)
        env.clock.step(LEASE_DURATION_S + 1)
        env.cluster = op_b.cluster
        env.kube.put_pod(Pod(requests=Resources(cpu=1, memory="2Gi")))
        for _ in range(12):
            env.clock.step(2.0)
            env.kubelet.step()
            op_b.reconcile_once()
            env.kubelet.step()
            if not env.kube.pending_pods():
                break
        assert b.leading
        assert not env.kube.pending_pods()
        assert reg_b.counters.get("karpenter_controller_reconcile_total")

    def test_graceful_release_hands_over_immediately(self):
        env, a, b, op_b, reg_b = _two_replicas()
        env.step()
        assert a.leading
        a.release()  # SIGTERM path (__main__.py frees the lease)
        op_b.reconcile_once()  # no expiry wait needed
        assert b.leading

    def test_mid_tick_abdication_stops_remaining_controllers(self):
        """The background renewal thread flips `leading` False when the
        lease is lost; the tick must stop before the next controller
        mutates anything."""
        env, a, b, op_b, reg_b = _two_replicas()
        env.step()  # A leads
        orig = env.operator.provisioner.reconcile

        def lose_lease_during_provisioning():
            orig()
            a.leading = False  # what the renewal thread does on loss

        env.operator.provisioner.reconcile = lose_lease_during_provisioning

        def count(name):
            series = env.registry.counters.get(
                "karpenter_controller_reconcile_total", {}
            )
            return series.get((("controller", name),), 0)

        prov_before = count("provisioner")
        term_before = count("termination")
        env.operator.reconcile_once()
        assert count("provisioner") == prov_before + 1  # ran, then lost
        assert count("termination") == term_before  # never reached
