"""v1alpha -> v1beta1 conversion (reference pkg/apis/v1alpha5/v1alpha1 +
the karpenter-convert migration mapping): converted objects must pass
admission validation and drive the real controller loop."""

import pytest

from karpenter_tpu.api import Pod, Resources
from karpenter_tpu.api import labels as L
from karpenter_tpu.api.legacy import (
    ConversionError,
    convert_aws_node_template,
    convert_provisioner,
)
from karpenter_tpu.testing import Environment

PROVISIONER = {
    "apiVersion": "karpenter.sh/v1alpha5",
    "kind": "Provisioner",
    "metadata": {"name": "default"},
    "spec": {
        "providerRef": {"name": "default"},
        "weight": 10,
        "labels": {"team": "ml"},
        "taints": [{"key": "dedicated", "value": "ml", "effect": "NoSchedule"}],
        "requirements": [
            {"key": L.LABEL_CAPACITY_TYPE, "operator": "In", "values": ["spot"]},
            {"key": L.LABEL_INSTANCE_CPU, "operator": "Lt", "values": ["33"]},
        ],
        "limits": {"resources": {"cpu": "100", "memory": "400Gi"}},
        "ttlSecondsUntilExpired": 2592000,
        "consolidation": {"enabled": True},
        "kubeletConfiguration": {"maxPods": 58},
    },
}

NODE_TEMPLATE = {
    "apiVersion": "karpenter.k8s.aws/v1alpha1",
    "kind": "AWSNodeTemplate",
    "metadata": {"name": "default"},
    "spec": {
        "amiFamily": "Bottlerocket",
        "subnetSelector": {"karpenter.sh/discovery": "my-cluster"},
        "securityGroupSelector": {"Name": "my-sg"},
        "tags": {"team": "ml"},
        "userData": "[settings.host]\nmotd = \"hi\"",
        "blockDeviceMappings": [
            {"deviceName": "/dev/xvdb", "ebs": {"volumeSize": "100Gi",
                                                "volumeType": "gp2",
                                                "encrypted": False}}
        ],
    },
}


class TestProvisionerConversion:
    def test_field_mapping(self):
        pool = convert_provisioner(PROVISIONER)
        assert pool.name == "default"
        assert pool.weight == 10
        assert pool.node_class_ref == "default"
        assert pool.labels == {"team": "ml"}
        assert pool.taints[0].key == "dedicated"
        assert pool.kubelet_max_pods == 58
        assert pool.limits.get("cpu") == 100
        assert pool.disruption.consolidation_policy == "WhenUnderutilized"
        assert pool.disruption.expire_after == 2592000.0
        zr = pool.requirements.get(L.LABEL_CAPACITY_TYPE)
        assert zr is not None and zr.has("spot")

    def test_ttl_after_empty_maps_to_when_empty(self):
        raw = {
            "kind": "Provisioner",
            "metadata": {"name": "p"},
            "spec": {"ttlSecondsAfterEmpty": 30},
        }
        pool = convert_provisioner(raw)
        assert pool.disruption.consolidation_policy == "WhenEmpty"
        assert pool.disruption.consolidate_after == 30.0

    def test_legacy_dialect_defaults_applied(self):
        """The v1alpha5 defaulting webhook pinned capacity-type to
        on-demand; conversion must preserve that (otherwise migration
        silently moves workloads to spot)."""
        pool = convert_provisioner(
            {"kind": "Provisioner", "metadata": {"name": "p"}, "spec": {}}
        )
        ct = pool.requirements.get(L.LABEL_CAPACITY_TYPE)
        assert ct is not None and ct.has("on-demand") and not ct.has("spot")
        # and "consolidation off" must never reap empty nodes
        assert pool.disruption.consolidate_after == float("inf")

    def test_mutual_exclusion_enforced(self):
        raw = {
            "kind": "Provisioner",
            "metadata": {"name": "p"},
            "spec": {"ttlSecondsAfterEmpty": 30,
                     "consolidation": {"enabled": True}},
        }
        with pytest.raises(ConversionError, match="mutually"):
            convert_provisioner(raw)

    def test_inline_provider_rejected(self):
        raw = {
            "kind": "Provisioner",
            "metadata": {"name": "p"},
            "spec": {"provider": {"subnetSelector": {}}},
        }
        with pytest.raises(ConversionError, match="providerRef"):
            convert_provisioner(raw)


class TestNodeTemplateConversion:
    def test_field_mapping(self):
        nc = convert_aws_node_template(NODE_TEMPLATE)
        assert nc.name == "default"
        assert nc.image_family == "accelerated"  # Bottlerocket analogue
        (term,) = nc.subnet_selector_terms
        assert term.tags == (("karpenter.sh/discovery", "my-cluster"),)
        (sg,) = nc.security_group_selector_terms
        assert sg.tags == (("Name", "my-sg"),)
        assert nc.user_data.startswith("[settings.host]")
        (bdm,) = nc.block_device_mappings
        assert bdm.device_name == "/dev/xvdb"
        assert bdm.volume_size == 100 * 2**30
        assert bdm.volume_type == "gp2" and bdm.encrypted is False

    def test_aws_ids_selector(self):
        raw = {
            "kind": "AWSNodeTemplate",
            "metadata": {"name": "t"},
            "spec": {"subnetSelector": {"aws-ids": "subnet-1, subnet-2"}},
        }
        nc = convert_aws_node_template(raw)
        assert [t.id for t in nc.subnet_selector_terms] == [
            "subnet-1", "subnet-2",
        ]

    def test_unknown_family_rejected(self):
        raw = {
            "kind": "AWSNodeTemplate",
            "metadata": {"name": "t"},
            "spec": {"amiFamily": "Windows2022"},
        }
        with pytest.raises(ConversionError, match="amiFamily"):
            convert_aws_node_template(raw)


class TestEndToEnd:
    def test_converted_objects_drive_the_controller(self):
        """A converted legacy pair must pass admission (KubeStore write
        validation) and provision real capacity for a matching pod."""
        env = Environment()
        tmpl = {
            "kind": "AWSNodeTemplate",
            "metadata": {"name": "default"},
            "spec": {"subnetSelector": {"Name": "*"},
                     "securityGroupSelector": {"Name": "*"}},
        }
        prov = {
            "kind": "Provisioner",
            "metadata": {"name": "default"},
            "spec": {
                "providerRef": {"name": "default"},
                "consolidation": {"enabled": True},
                "taints": [{"key": "dedicated", "value": "ml",
                            "effect": "NoSchedule"}],
            },
        }
        env.kube.put_node_class(convert_aws_node_template(tmpl))
        env.kube.put_node_pool(convert_provisioner(prov))
        from karpenter_tpu.api.objects import Toleration

        env.kube.put_pod(
            Pod(
                requests=Resources(cpu=1, memory="1Gi"),
                tolerations=[Toleration(key="dedicated", value="ml")],
            )
        )
        env.settle()
        assert not env.kube.pending_pods()
        assert env.kube.node_claims
