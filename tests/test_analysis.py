"""Unit tests for the static-analysis plane itself (karpenter_tpu/analysis/):
the engine's structured findings and stable JSON, the call graph's
resolution rules, the lock model, per-analyzer positive/negative/
allowlisted/baselined behavior on synthetic trees, and the pinned
regressions for the real violations the analyzers surfaced (the
uncounted jit dispatches in fetch_bundled and the solver sidecar)."""

from __future__ import annotations

import ast
import json
import sys

import numpy as np
import pytest

from karpenter_tpu.analysis import (
    Finding,
    PackageSnapshot,
    RULES,
    load_baseline,
    run_rules,
    to_report,
)
from karpenter_tpu.analysis.allowlists import ALLOWLISTS
from karpenter_tpu.analysis.graph import CallGraph
from karpenter_tpu.analysis.locks import build_lock_model


def forge(tmp_path, files: dict, pkg_name: str = "forged") -> PackageSnapshot:
    pkg = tmp_path / pkg_name
    for rel, src in files.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return PackageSnapshot(pkg)


# ----------------------------------------------------------------- engine
class TestEngine:
    def test_snapshot_indexes_modules(self, tmp_path):
        snap = forge(
            tmp_path,
            {"__init__.py": "", "a.py": "x = 1\n", "sub/b.py": "y = 2\n"},
        )
        assert sorted(m.rel_in_pkg for m in snap.in_package()) == [
            "__init__.py", "a.py", "sub/b.py",
        ]
        assert snap.modules["forged/sub/b.py"].name == "forged.sub.b"
        assert list(snap.in_package("sub/"))[0].rel == "forged/sub/b.py"

    def test_parse_error_is_a_finding_not_a_crash(self, tmp_path):
        snap = forge(tmp_path, {"bad.py": "def broken(:\n"})
        live, _ = run_rules(snap, rule_names=["wall-clock"])
        assert len(live) == 1 and live[0].rule == "parse"

    def test_fingerprint_survives_line_drift(self):
        a = Finding(rule="r", file="f.py", line=3, message="m")
        b = Finding(rule="r", file="f.py", line=30, message="m")
        c = Finding(rule="r", file="f.py", line=3, message="other")
        assert a.fingerprint == b.fingerprint != c.fingerprint

    def test_json_report_is_stable_and_sorted(self, tmp_path):
        """The CI-diffable contract: same tree -> byte-identical JSON,
        findings sorted, no wall-clock field without --profile."""
        files = {
            "x.py": "import time\nb = time.sleep(1)\na = time.time()\n"
        }
        reports = []
        for _ in range(2):
            snap = forge(tmp_path, files)
            live, supp = run_rules(
                snap, rule_names=["wall-clock"],
                allowlists={"wall-clock": frozenset()},
            )
            reports.append(
                json.dumps(
                    to_report(snap, live, supp, ["wall-clock"]),
                    sort_keys=True,
                )
            )
        assert reports[0] == reports[1]
        report = json.loads(reports[0])
        assert report["version"] == 1
        assert "timings_s" not in report
        lines = [f["line"] for f in report["findings"]]
        assert lines == sorted(lines) and len(lines) == 2
        assert set(report["findings"][0]) == {
            "rule", "file", "line", "message", "fingerprint",
        }

    def test_identical_findings_get_distinct_fingerprints(self, tmp_path):
        """Two byte-identical violations in one file must not share a
        fingerprint — baselining the known one cannot silently suppress
        a fresh duplicate (review finding)."""
        snap = forge(
            tmp_path,
            {"x.py": "import time\nx = time.time()\nx = time.time()\n"},
        )
        live, _ = run_rules(
            snap, rule_names=["wall-clock"],
            allowlists={"wall-clock": frozenset()},
        )
        assert len(live) == 2
        assert live[0].fingerprint != live[1].fingerprint
        live2, suppressed = run_rules(
            snap, rule_names=["wall-clock"],
            allowlists={"wall-clock": frozenset()},
            baseline={live[0].fingerprint: "known"},
        )
        assert len(live2) == 1 and len(suppressed) == 1

    def test_allowlist_table_names_only_registered_rules(self):
        assert set(ALLOWLISTS) <= set(RULES)

    def test_cli_baseline_file_suppresses(self, tmp_path):
        from karpenter_tpu.analysis.cli import main as lint_main

        pkg = tmp_path / "forged"
        pkg.mkdir()
        (pkg / "x.py").write_text("import time\na = time.time()\n")
        assert lint_main(
            ["--root", str(pkg), "--rule", "wall-clock"]
        ) == 1
        snap = PackageSnapshot(pkg)
        live, _ = run_rules(
            snap, rule_names=["wall-clock"],
            allowlists={"wall-clock": frozenset()},
        )
        baseline = tmp_path / "baseline.json"
        baseline.write_text(
            json.dumps(
                {
                    "version": 1,
                    "suppressions": [
                        {"fingerprint": live[0].fingerprint, "note": "known"}
                    ],
                }
            )
        )
        assert load_baseline(baseline) == {live[0].fingerprint: "known"}
        assert lint_main(
            [
                "--root", str(pkg), "--rule", "wall-clock",
                "--baseline", str(baseline),
            ]
        ) == 0


# ------------------------------------------------------------ call graph
class TestCallGraph:
    def test_self_super_and_import_resolution(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "base.py": "class Base:\n    def ping(self):\n        pass\n",
                "x.py": (
                    "from forged.base import Base\n"
                    "from forged.util import helper\n"
                    "class C(Base):\n"
                    "    def a(self):\n"
                    "        self.b()\n"
                    "        super().ping()\n"
                    "        helper()\n"
                    "    def b(self):\n"
                    "        pass\n"
                ),
                "util.py": "def helper():\n    pass\n",
            },
        )
        g = CallGraph(snap)
        callees = g.defs["forged/x.py:C.a"].callees
        assert "forged/x.py:C.b" in callees
        assert "forged/base.py:Base.ping" in callees
        assert "forged/util.py:helper" in callees

    def test_inherited_method_resolves_through_base(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "x.py": (
                    "class Base:\n"
                    "    def work(self):\n"
                    "        pass\n"
                    "class C(Base):\n"
                    "    def a(self):\n"
                    "        self.work()\n"
                ),
            },
        )
        g = CallGraph(snap)
        assert "forged/x.py:Base.work" in g.defs["forged/x.py:C.a"].callees

    def test_relative_import_in_package_init_resolves(self, tmp_path):
        """``from .sub import f`` inside an __init__.py resolves against
        the package itself, not its parent (review finding: the stripped
        ``.__init__`` suffix shifted the level arithmetic one up)."""
        snap = forge(
            tmp_path,
            {
                "__init__.py": (
                    "from .util import helper\n"
                    "def top():\n"
                    "    return helper()\n"
                ),
                "util.py": "def helper():\n    pass\n",
            },
        )
        g = CallGraph(snap)
        assert "forged/util.py:helper" in g.defs["forged/__init__.py:top"].callees

    def test_stoplisted_names_do_not_alias_globally(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "a.py": "class A:\n    def get(self):\n        pass\n",
                "b.py": "def f(cache):\n    cache.get('k')\n",
            },
        )
        g = CallGraph(snap)
        assert g.defs["forged/b.py:f"].callees == set()


# ------------------------------------------------------------ lock model
class TestLockModel:
    def test_discovery_and_condition_alias(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "x.py": (
                    "import threading\n"
                    "class S:\n"
                    "    def __init__(self):\n"
                    "        self._lk = threading.Lock()\n"
                    "        self._cv = threading.Condition(self._lk)\n"
                ),
            },
        )
        model = build_lock_model(snap)
        assert model.owners[("S", "_lk")] == "Lock"
        assert model.canonical("S._cv") == "S._lk"

    def test_condition_alias_means_no_self_edge(self, tmp_path):
        """Holding the Condition IS holding the wrapped lock: nesting
        them must not read as a lock-order edge."""
        snap = forge(
            tmp_path,
            {
                "service/x.py": (
                    "import threading\n"
                    "class S:\n"
                    "    def __init__(self):\n"
                    "        self._lk = threading.Lock()\n"
                    "        self._cv = threading.Condition(self._lk)\n"
                    "    def a(self):\n"
                    "        with self._lk:\n"
                    "            with self._cv:\n"
                    "                pass\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["lock-order"],
            allowlists={"lock-order": frozenset()},
        )
        assert not live, [f.render() for f in live]

    def test_consistent_order_is_clean(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "service/x.py": (
                    "import threading\n"
                    "class S:\n"
                    "    def __init__(self):\n"
                    "        self._a = threading.Lock()\n"
                    "        self._b = threading.Lock()\n"
                    "    def one(self):\n"
                    "        with self._a:\n"
                    "            with self._b:\n"
                    "                pass\n"
                    "    def two(self):\n"
                    "        with self._a:\n"
                    "            with self._b:\n"
                    "                pass\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["lock-order"],
            allowlists={"lock-order": frozenset()},
        )
        assert not live, [f.render() for f in live]

    def test_transitive_inversion_through_calls(self, tmp_path):
        """The inversion hides one call deep on each side — the exact
        shape a per-callsite rule cannot express."""
        snap = forge(
            tmp_path,
            {
                "service/x.py": (
                    "import threading\n"
                    "class S:\n"
                    "    def __init__(self):\n"
                    "        self._a = threading.Lock()\n"
                    "        self._b = threading.Lock()\n"
                    "    def fwd(self):\n"
                    "        with self._a:\n"
                    "            self._take_b()\n"
                    "    def _take_b(self):\n"
                    "        with self._b:\n"
                    "            pass\n"
                    "    def rev(self):\n"
                    "        with self._b:\n"
                    "            self._take_a()\n"
                    "    def _take_a(self):\n"
                    "        with self._a:\n"
                    "            pass\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["lock-order"],
            allowlists={"lock-order": frozenset()},
        )
        assert len(live) == 1 and "inversion" in live[0].message

    def test_lock_finding_fingerprints_survive_line_drift(self, tmp_path):
        """Lock messages must not embed line numbers: a baselined lock
        finding survives unrelated drift in the callee file (review
        finding: site strings carried ':line')."""
        src = (
            "import threading\n"
            "from forged.service.codec import send_frame\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lk = threading.Lock()\n"
            "    def push(self, sock, payload):\n"
            "        with self._lk:\n"
            "            send_frame(sock, payload)\n"
        )
        codec = "def send_frame(s, b):\n    s.sendall(b)\n"
        snap1 = forge(tmp_path / "v1", {"service/x.py": src,
                                        "service/codec.py": codec})
        snap2 = forge(
            tmp_path / "v2",
            {"service/x.py": src,
             "service/codec.py": "import io\n\n\n" + codec},
        )
        fps = []
        for snap in (snap1, snap2):
            live, _ = run_rules(
                snap, rule_names=["lock-blocking"],
                allowlists={"lock-blocking": frozenset()},
            )
            assert live
            fps.append(sorted(f.fingerprint for f in live))
        assert fps[0] == fps[1]

    def test_multi_item_with_records_order_edges(self, tmp_path):
        """``with self._a, self._b:`` acquires in item order — an
        inversion against the nested form in another method must still
        be caught (review finding: sibling items were invisible)."""
        snap = forge(
            tmp_path,
            {
                "service/x.py": (
                    "import threading\n"
                    "class S:\n"
                    "    def __init__(self):\n"
                    "        self._a = threading.Lock()\n"
                    "        self._b = threading.Lock()\n"
                    "    def fwd(self):\n"
                    "        with self._a, self._b:\n"
                    "            pass\n"
                    "    def rev(self):\n"
                    "        with self._b:\n"
                    "            with self._a:\n"
                    "                pass\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["lock-order"],
            allowlists={"lock-order": frozenset()},
        )
        assert len(live) == 1 and "inversion" in live[0].message

    def test_blocking_negative_outside_lock(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "service/x.py": (
                    "import threading\n"
                    "from forged.service.codec import send_frame\n"
                    "class S:\n"
                    "    def __init__(self):\n"
                    "        self._lk = threading.Lock()\n"
                    "    def push(self, sock, payload):\n"
                    "        with self._lk:\n"
                    "            n = len(payload)\n"
                    "        send_frame(sock, payload)\n"
                ),
                "service/codec.py": "def send_frame(s, b):\n    s.sendall(b)\n",
            },
        )
        live, _ = run_rules(
            snap, rule_names=["lock-blocking"],
            allowlists={"lock-blocking": frozenset()},
        )
        assert not live, [f.render() for f in live]


# ---------------------------------------------------------- reachability
class TestReachability:
    def test_moved_root_is_a_finding_on_the_real_package_name(
        self, tmp_path
    ):
        """A refactor that moves a byte-compared surface must not
        silently drop it out of coverage."""
        snap = forge(
            tmp_path, {"x.py": "a = 1\n"}, pkg_name="karpenter_tpu"
        )
        live, _ = run_rules(
            snap, rule_names=["determinism-reachability"],
            allowlists={"determinism-reachability": frozenset()},
        )
        assert live and all("no longer resolves" in f.message for f in live)

    def test_injected_clock_path_is_clean(self, tmp_path):
        """Reading time through an injected clock attribute (the Clock
        pattern) is exactly what the rule wants to see."""
        snap = forge(
            tmp_path,
            {
                "sim/trace.py": (
                    "class TraceWriter:\n"
                    "    def __init__(self, clock):\n"
                    "        self.clock = clock\n"
                    "    def digest(self, tick, env):\n"
                    "        return {'tick': tick, 'now': self.clock.now()}\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["determinism-reachability"],
            allowlists={"determinism-reachability": frozenset()},
        )
        assert not live, [f.render() for f in live]

    def test_alias_cannot_hide_the_wall_clock(self, tmp_path):
        """``import time as _time`` (the clock.py idiom) still taints."""
        snap = forge(
            tmp_path,
            {
                "sim/trace.py": (
                    "import time as _t\n"
                    "class TraceWriter:\n"
                    "    def digest(self, tick, env):\n"
                    "        return _t.time()\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["determinism-reachability"],
            allowlists={"determinism-reachability": frozenset()},
        )
        assert len(live) == 1 and "wall clock" in live[0].message

    def test_from_import_and_dotted_chain_cannot_hide_the_clock(
        self, tmp_path
    ):
        """``from time import time`` (bare call) and
        ``datetime.datetime.now()`` (dotted chain) both taint (review
        finding: only `Name.attr(...)` calls were matched)."""
        snap = forge(
            tmp_path,
            {
                "sim/trace.py": (
                    "import datetime\n"
                    "from time import time\n"
                    "class TraceWriter:\n"
                    "    def digest(self, tick, env):\n"
                    "        return (time(), datetime.datetime.now())\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["determinism-reachability"],
            allowlists={"determinism-reachability": frozenset()},
        )
        msgs = "\n".join(f.message for f in live)
        assert "wall clock time.time()" in msgs
        assert "datetime.now()" in msgs

    def test_set_iteration_feeding_root_is_tainted(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "sim/report.py": (
                    "def build_report(reg):\n"
                    "    out = []\n"
                    "    for k in set(reg):\n"
                    "        out.append(k)\n"
                    "    return out\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["determinism-reachability"],
            allowlists={"determinism-reachability": frozenset()},
        )
        assert len(live) == 1 and "set(...)" in live[0].message


class TestTracerDiscovery:
    def test_nested_jit_does_not_shadow_module_level_namesake(
        self, tmp_path
    ):
        """A factory-local jit def named like a module-level jit def
        must not evict the module-level one from coverage (review
        finding: the nested walk clobbered then popped it)."""
        snap = forge(
            tmp_path,
            {
                "ops/x.py": (
                    "import jax\n"
                    "@jax.jit\n"
                    "def step(x):\n"
                    "    print('trace-time')\n"
                    "    return x\n"
                    "def factory():\n"
                    "    def step(y):\n"
                    "        return y\n"
                    "    return jax.jit(step)\n"
                    "def caller(x):\n"
                    "    return step(x)\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["tracer-safety"],
            allowlists={"tracer-safety": frozenset()},
        )
        msgs = "\n".join(f.message for f in live)
        # body of the MODULE-LEVEL step still linted...
        assert "print(...) inside traced body" in msgs
        # ...and its direct call site still fenced
        assert "direct call of jit callable step" in msgs

    def test_traced_param_sort_is_not_flagged(self, tmp_path):
        """``x.sort()`` on a traced parameter is the FUNCTIONAL
        jax.numpy method (tracers are not ndarrays) — must stay clean
        (review finding: it was flagged as in-place mutation)."""
        snap = forge(
            tmp_path,
            {
                "ops/x.py": (
                    "import jax\n"
                    "@jax.jit\n"
                    "def kernel(x):\n"
                    "    y = x.sort()\n"
                    "    return y\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["tracer-safety"],
            allowlists={"tracer-safety": frozenset()},
        )
        assert not live, [f.render() for f in live]


# -------------------------------------------------------- runtime rules
class TestRuntimeRules:
    def test_import_clean_teeth(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "__init__.py": "",
                "ok.py": "x = 1\n",
                "broken.py": "import no_such_module_xyzzy\n",
            },
            pkg_name="forged_import_teeth",
        )
        try:
            live, _ = run_rules(snap, rule_names=["import-clean"])
            assert len(live) == 1
            assert "broken" in live[0].file and "failed to import" in (
                live[0].message
            )
        finally:
            for name in list(sys.modules):
                if name.startswith("forged_import_teeth"):
                    del sys.modules[name]

    def test_annotations_resolve_teeth(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "__init__.py": "",
                "bad.py": "def f(x: 'Optional[int]' = None):\n    return x\n",
            },
            pkg_name="forged_anno_teeth",
        )
        try:
            live, _ = run_rules(snap, rule_names=["annotations-resolve"])
            assert len(live) == 1
            assert "unresolvable annotation" in live[0].message
        finally:
            for name in list(sys.modules):
                if name.startswith("forged_anno_teeth"):
                    del sys.modules[name]


# ------------------------------------------- pinned real-fix regressions
class TestSeamRegressions:
    """The violations the tracer-safety analyzer surfaced on the real
    package, fixed and pinned: both jit dispatch sites now count into
    the device observatory."""

    def test_fetch_bundled_counts_through_the_seam(self):
        import jax.numpy as jnp

        from karpenter_tpu.obs.device import OBSERVATORY
        from karpenter_tpu.ops.packer import fetch_bundled

        class Res:
            take = jnp.zeros((2, 4), jnp.int32)
            leftover = jnp.zeros(2, jnp.int32)
            node_cfg = jnp.zeros((2,), jnp.int32)
            node_used = jnp.zeros((2, 3), jnp.float32)
            # no `bundle` attribute: forces the on-device bundling path

        scope = OBSERVATORY.begin_scope()
        try:
            take, leftover, node_cfg, node_used = fetch_bundled(Res())
        finally:
            OBSERVATORY.end_scope(scope)
        assert scope.dispatches.get("bundle_outputs") == 1
        assert take.shape == (2, 4) and node_used.shape == (2, 3)

    def test_sidecar_pack_counts_through_the_seam(self):
        from karpenter_tpu.api import Pod, Resources
        from karpenter_tpu.obs.device import OBSERVATORY
        from karpenter_tpu.ops.tensorize import compile_problem
        from karpenter_tpu.service import RemoteSolver, SolverServer
        from karpenter_tpu.testing import Environment

        env = Environment()
        pool = env.default_node_pool()
        env.default_node_class()
        types = env.instance_types.list(
            pool, env.kube.get_node_class("default")
        )
        pods = [Pod(requests=Resources(cpu=1, memory="1Gi")) for _ in range(8)]
        prob = compile_problem(pods, [pool], {pool.name: types})
        srv = SolverServer(port=0).start_background()
        scope = OBSERVATORY.begin_scope()
        try:
            client = RemoteSolver(*srv.address)
            client.pack_problem(prob)
            client.close()
        finally:
            OBSERVATORY.end_scope(scope)
            srv.stop()
        # the sidecar's kernel dispatch now lands in ITS process
        # observatory (same process here), wire arrays counted as the
        # real host->device upload
        assert scope.dispatches.get("pack_kernel", 0) >= 1
        assert scope.transfer_bytes.get("pack_kernel", 0) > 0


# ------------------------------------------------------- load-harness teeth
class TestLoadHarnessCoverage:
    """The analyzers must keep their teeth over the load harness: the
    wall-clock rule scans load/, and the determinism analyzer treats
    `EventTape.digest` (the tape identity hash) as a byte-compared root."""

    def test_wall_clock_rule_covers_load_package(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "load/generators.py": (
                    "import time\n"
                    "def build():\n"
                    "    return time.time()\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["wall-clock"],
            allowlists={"wall-clock": frozenset()},
        )
        assert len(live) == 1
        assert live[0].file.endswith("load/generators.py")

    def test_tape_digest_root_taints_reachable_wall_clock(self, tmp_path):
        """A wall-clock read reachable from `EventTape.digest` — here via
        the column-build helper the digest hashes — is a finding: the
        tape's identity hash is a byte-compared surface."""
        snap = forge(
            tmp_path,
            {
                "load/generators.py": (
                    "import time\n"
                    "def _stamp():\n"
                    "    return time.time()\n"
                    "class EventTape:\n"
                    "    def digest(self):\n"
                    "        return str(_stamp())\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["determinism-reachability"],
            allowlists={"determinism-reachability": frozenset()},
        )
        assert len(live) == 1 and "wall clock" in live[0].message

    def test_tape_digest_root_clean_tree_has_no_findings(self, tmp_path):
        snap = forge(
            tmp_path,
            {
                "load/generators.py": (
                    "import hashlib\n"
                    "import json\n"
                    "class EventTape:\n"
                    "    def __init__(self, seed):\n"
                    "        self.seed = seed\n"
                    "    def digest(self):\n"
                    "        h = hashlib.sha256()\n"
                    "        h.update(json.dumps(self.seed).encode())\n"
                    "        return h.hexdigest()\n"
                ),
            },
        )
        live, _ = run_rules(
            snap, rule_names=["determinism-reachability"],
            allowlists={"determinism-reachability": frozenset()},
        )
        assert not live, [f.render() for f in live]
