"""Pool-priority spill (round-2 VERDICT weak item: the untested case of
the host-side pool-priority pass): when the top-weight pool's limits fill
mid-batch, the remaining pods must spill to the next pool by weight
instead of ping-ponging on the full pool (reference
designs/provisioner-priority.md + designs/limits.md)."""

import pytest

from karpenter_tpu.api import Disruption, Pod, Resources
from karpenter_tpu.testing import Environment

# consolidation frozen so the spill outcome stays observable (it would
# otherwise legally repack everything onto one big overflow node)
_NO_CONSOLIDATION = Disruption(consolidation_policy="WhenEmpty")


@pytest.fixture()
def env():
    return Environment()


def test_spill_to_lower_weight_pool_when_limits_fill(env):
    env.default_node_class()
    # reserved: preferred but tiny — fits roughly one mid-size node
    env.default_node_pool(
        name="reserved", weight=100, limits=Resources(cpu=40),
        disruption=_NO_CONSOLIDATION,
    )
    env.default_node_pool(name="overflow", weight=0, disruption=_NO_CONSOLIDATION)
    pods = [Pod(requests=Resources(cpu=4, memory="8Gi")) for _ in range(40)]
    for p in pods:
        env.kube.put_pod(p)
    env.settle(max_rounds=40)
    assert not env.kube.pending_pods(), len(env.kube.pending_pods())

    by_pool = {}
    for claim in env.kube.node_claims.values():
        by_pool.setdefault(claim.pool_name, []).append(claim)
    # the preferred pool was used...
    assert "reserved" in by_pool, by_pool.keys()
    # ...within its limits...
    reserved_cpu = sum(c.capacity.cpu for c in by_pool["reserved"])
    assert reserved_cpu <= 40
    # ...and the overflow landed on the lower-weight pool
    assert "overflow" in by_pool, by_pool.keys()


def test_zero_limit_pool_provisions_nothing(env):
    """`limits: {cpu: 0}` means "provision nothing" (the pause idiom,
    api/resources.py), NOT "unlimited"."""
    env.default_node_class()
    env.default_node_pool(name="paused", limits=Resources(cpu=0))
    env.kube.put_pod(Pod(requests=Resources(cpu=1)))
    for _ in range(5):
        env.step(2.0)
    assert not env.kube.node_claims
    assert env.kube.pending_pods()


def test_existing_node_placement_survives_full_pools(env):
    """Even with every pool limited out, the solve must still run so
    pending pods land on existing nodes with free capacity."""
    env.default_node_class()
    pool = env.default_node_pool(name="only", disruption=_NO_CONSOLIDATION)
    first = Pod(requests=Resources(cpu=2, memory="4Gi"))
    env.kube.put_pod(first)
    env.settle()
    assert not env.kube.pending_pods()
    # clamp the pool shut AFTER the first node exists
    pool.limits = Resources(cpu=0.5)
    second = Pod(requests=Resources(cpu=1, memory="1Gi"))
    env.kube.put_pod(second)
    env.settle()
    assert not env.kube.pending_pods()
    assert len(env.kube.node_claims) == 1  # no new node: placed on existing
    assert env.kube.pods[second.key()].node_name


def test_all_pools_at_limit_reports_unschedulable(env):
    env.default_node_class()
    env.default_node_pool(name="only", weight=10, limits=Resources(cpu=1))
    pod = Pod(requests=Resources(cpu=4))
    env.kube.put_pod(pod)
    for _ in range(5):
        env.step(2.0)
    # the limited pool offers only <=1-cpu shapes; the 4-cpu pod cannot
    # schedule and says so instead of looping silently
    assert env.kube.pending_pods()
    assert any(
        e[1] == "FailedScheduling" and e[2] == pod.key()
        for e in env.kube.events
    ), env.kube.events[-5:]
