"""Metrics breadth + the round-4 exporters (VERDICT item: >=30 named
metrics with the cloudprovider decorator, batcher export, per-type
gauges, interruption latency — reference website v0.31 concepts/metrics.md,
cmd/controller/main.go:46, pkg/batcher/metrics.go,
pkg/providers/instancetype/metrics.go, interruption/metrics.go)."""

import re

import pytest

from karpenter_tpu.api import Disruption, Pod, Resources, Settings
from karpenter_tpu.testing import Environment


@pytest.fixture()
def env():
    return Environment(
        settings=Settings(cluster_name="test", interruption_queue_name="q")
    )


def _drive(env):
    """Exercise provision -> interruption -> consolidation -> termination
    so every metric family gets at least one sample."""
    env.default_node_class()
    env.default_node_pool(
        limits=Resources(cpu=1000),
        disruption=Disruption(consolidation_policy="WhenUnderutilized"),
    )
    pods = [Pod(requests=Resources(cpu=2, memory="4Gi")) for _ in range(20)]
    for p in pods:
        env.kube.put_pod(p)
    env.settle()
    assert not env.kube.pending_pods()
    claim = next(iter(env.kube.node_claims.values()))
    env.cloud.send_message(
        {"kind": "rebalance_recommendation", "instance_id": claim.provider_id}
    )
    env.step(2.0)
    for p in pods[10:]:
        env.kube.delete_pod(p.key())
    for _ in range(20):
        env.step(2.0)


class TestMetricsBreadth:
    def test_at_least_30_distinct_names(self, env):
        _drive(env)
        dump = env.registry.dump()
        names = {
            re.sub(
                r"_(count|sum)$", "", line.split("{")[0].split(" ")[0]
            )
            for line in dump.splitlines()
        }
        karpenter = {n for n in names if n.startswith("karpenter_")}
        assert len(karpenter) >= 30, sorted(karpenter)

    def test_cloudprovider_decorator(self, env):
        _drive(env)
        r = env.registry
        assert r.histogram(
            "karpenter_cloudprovider_duration_seconds",
            {"method": "create", "provider": "karpenter-tpu"},
        )
        assert r.histogram(
            "karpenter_cloudprovider_duration_seconds",
            {"method": "list", "provider": "karpenter-tpu"},
        )

    def test_cloudprovider_error_counter(self, env):
        env.default_node_class()
        from karpenter_tpu.api import NodeClaim
        from karpenter_tpu.errors import NodeClaimNotFoundError

        with pytest.raises(NodeClaimNotFoundError):
            env.cloud_provider.get("i-does-not-exist")
        assert (
            env.registry.counter(
                "karpenter_cloudprovider_errors_total",
                {
                    "method": "get",
                    "provider": "karpenter-tpu",
                    "error": "NodeClaimNotFoundError",
                },
            )
            == 1
        )

    def test_batcher_export(self, env):
        _drive(env)
        sizes = env.registry.histogram(
            "karpenter_cloudprovider_batcher_batch_size",
            {"batcher": "create-fleet"},
        )
        assert sizes and max(sizes) >= 1
        times = env.registry.histogram(
            "karpenter_cloudprovider_batcher_batch_time_seconds",
            {"batcher": "create-fleet"},
        )
        assert times

    def test_instance_type_gauges(self, env):
        env.default_node_class()
        pool = env.default_node_pool()
        types = env.instance_types.list(pool, env.kube.node_classes["default"])
        it = types[0]
        assert env.registry.gauge(
            "karpenter_cloudprovider_instance_type_cpu_cores",
            {"instance_type": it.name},
        ) == it.capacity.cpu
        off = it.offerings[0]
        assert env.registry.gauge(
            "karpenter_cloudprovider_instance_type_price_estimate",
            {
                "instance_type": it.name,
                "capacity_type": off.capacity_type,
                "zone": off.zone,
            },
        ) == off.price

    def test_interruption_latency_and_actions(self, env):
        _drive(env)
        r = env.registry
        lat = r.histogram("karpenter_interruption_message_latency_time_seconds")
        assert lat and all(v >= 0 for v in lat)
        assert r.counter("karpenter_interruption_deleted_messages") >= 1
        assert (
            r.counter(
                "karpenter_interruption_actions_performed",
                {
                    "action": "CordonAndDrain",
                    "message_type": "rebalance_recommendation",
                },
            )
            >= 1
        )

    def test_state_gauges_and_reconcile_series(self, env):
        _drive(env)
        r = env.registry
        # per-node allocatable series exist with resource_type labels
        assert any(
            name == "karpenter_nodes_allocatable"
            for name in r.gauges
        )
        assert r.gauges["karpenter_nodes_allocatable"]
        # pools
        assert r.gauges["karpenter_provisioner_usage"]
        assert r.gauges["karpenter_provisioner_limit"]
        # reconcile series for every registered controller
        assert r.counter(
            "karpenter_controller_reconcile_total", {"controller": "provisioner"}
        ) > 0
        assert r.histogram(
            "karpenter_controller_reconcile_time_seconds",
            {"controller": "disruption"},
        )
        # pod lifecycle
        assert r.histogram("karpenter_pods_startup_time_seconds")
        # node termination latency observed after consolidation deletes
        assert r.histogram(
            "karpenter_nodes_termination_time_seconds", {"nodepool": "default"}
        )
