"""Shared cluster-store subsystem: server, client mirror, failure modes.

The StoreServer (service/store_server.py) is the kube-apiserver analogue:
one durable KubeStore behind the socket protocol; RemoteKubeStore
(state/remote.py) is the controller-side mirror.  These specs cover the
replication contract (writes visible across clients, watch keeps standby
mirrors warm, in-place mutations flush on lease ops) and the failure
modes a production deployment hits: store restart mid-watch (client
resyncs), request timeout (retryable error, not a hang), and
resourceVersion conflict on concurrent Lease renewal (loses cleanly).
"""

import socket
import struct
import threading
import time

import pytest

from karpenter_tpu.api import NodeClass, NodePool, Pod, Resources
from karpenter_tpu.api.objects import SelectorTerm
from karpenter_tpu.service.store_server import StoreServer, VersionedStore
from karpenter_tpu.state.kube import Node
from karpenter_tpu.state.remote import RemoteKubeStore, StoreUnavailableError
from karpenter_tpu.state.wire import canonical, from_wire, to_wire


@pytest.fixture
def server():
    srv = StoreServer().start_background()
    yield srv
    srv.stop()


def _client(server, **kw):
    host, port = server.address
    return RemoteKubeStore(host, port, **kw)


def _default_objects(kube):
    kube.put_node_class(
        NodeClass(
            name="default",
            subnet_selector_terms=[SelectorTerm.of(Name="*")],
            security_group_selector_terms=[SelectorTerm.of(Name="*")],
        )
    )
    kube.put_node_pool(NodePool(name="default", node_class_ref="default"))


class TestWireCodec:
    def test_round_trip_preserves_scheduling_semantics(self):
        from karpenter_tpu.api.objects import (
            PodAffinityTerm,
            TopologySpreadConstraint,
        )
        from karpenter_tpu.api.requirements import Op, Requirement

        pod = Pod(
            requests=Resources(cpu=1, memory="2Gi"),
            node_selector={"a": "b"},
            required_affinity=[Requirement("z", Op.IN, ["z1", "z2"])],
            preferred_affinity=[Requirement("z", Op.IN, ["z1"])],
            topology_spread=[
                TopologySpreadConstraint(
                    1, "zone", label_selector=(("x", "y"),)
                )
            ],
            pod_affinity=[
                PodAffinityTerm(
                    topology_key="kubernetes.io/hostname",
                    anti=True,
                    label_selector=(("x", "y"),),
                )
            ],
        )
        back = from_wire(to_wire(pod))
        assert canonical(back) == canonical(pod)
        # the deep equality that matters: identical grouping signature
        assert back.constraint_signature() == pod.constraint_signature()

    def test_no_arbitrary_class_instantiation(self):
        with pytest.raises(ValueError, match="unknown wire dataclass"):
            from_wire({"!dc": "Settings", "f": {}})
        with pytest.raises(ValueError, match="untagged"):
            from_wire({"cmd": "rm -rf /"})


class TestReplication:
    def test_writes_visible_across_clients(self, server):
        a = _client(server, identity="a")
        b = _client(server, identity="b")
        try:
            _default_objects(a)
            a.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
            assert b.wait_synced()
            assert "default" in b.node_pools
            assert "default/p1" in b.pods
            a.delete_pod("default/p1")
            assert b.wait_synced()
            assert "default/p1" not in b.pods
        finally:
            a.close()
            b.close()

    def test_semantic_verbs_run_server_side(self, server):
        """bind_pod's cascades (pod phase, zone anchoring) replicate."""
        a = _client(server, identity="a")
        b = _client(server, identity="b")
        try:
            a.put_node(Node(name="n1", ready=True))
            a.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
            a.bind_pod("default/p1", "n1")
            assert b.wait_synced()
            assert b.pods["default/p1"].node_name == "n1"
            assert b.pods["default/p1"].phase == "Running"
            assert server.store.kube.pods["default/p1"].node_name == "n1"
        finally:
            a.close()
            b.close()

    def test_in_place_mutations_flush_on_lease_ops(self, server):
        """Controllers stamp conditions/labels without calling put (e.g.
        lifecycle.py); the shadow diff pushes them before every lease
        operation — at least once per tick."""
        a = _client(server, identity="a")
        b = _client(server, identity="b")
        try:
            pod = a.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
            pod.phase = "Running"
            pod.node_name = "nx"  # in-place, no verb
            assert a.try_acquire_lease("el", "a", 100.0, 15.0)
            assert b.wait_synced()
            assert b.pods["default/p1"].node_name == "nx"
        finally:
            a.close()
            b.close()

    def test_stale_write_conflicts_and_adopts_server_state(self, server):
        """rv fencing: a deposed writer's straggler put loses to the
        newer write and the client adopts the server's object."""
        a = _client(server, identity="a")
        b = _client(server, identity="b")
        try:
            pod = a.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
            assert b.wait_synced()
            # b writes a newer version
            newer = b.pods["default/p1"]
            newer.labels["winner"] = "b"
            assert b.try_acquire_lease("el", "b", 1.0, 15.0)  # flush
            # a mutates its (now-stale) object and flushes with a stale rv
            a._shadow.pop(("Pod", "default/p1"), None)  # force dirty
            pod.labels["winner"] = "a"
            # stale base_rv: a hasn't absorbed b's write yet in the worst
            # case; simulate by pinning a's recorded rv backwards
            a.wait_synced()
            a._rvs[("Pod", "default/p1")] = 0
            a._flush_dirty()
            a.wait_synced()
            assert a.pods["default/p1"].labels.get("winner") == "b"
            assert (
                server.store.kube.pods["default/p1"].labels["winner"] == "b"
            )
        finally:
            a.close()
            b.close()


class TestStragglerFencing:
    def test_stale_delete_conflicts_and_restores(self, server):
        """A deposed leader's straggler DELETE is fenced exactly like a
        stale put: the server keeps the newer object and the straggler
        adopts it back into its mirror."""
        a = _client(server, identity="a")
        b = _client(server, identity="b")
        try:
            a.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
            assert b.wait_synced()
            newer = b.pods["default/p1"]
            newer.labels["owner"] = "b"
            assert b.try_acquire_lease("el", "b", 1.0, 15.0)  # flush
            # the deposed replica still holds the pre-update rv
            a._rvs[("Pod", "default/p1")] = 1
            a.delete_pod("default/p1")
            # server kept b's object; a adopted it back
            assert "default/p1" in server.store.kube.pods
            assert (
                server.store.kube.pods["default/p1"].labels["owner"] == "b"
            )
            assert "default/p1" in a.pods
        finally:
            a.close()
            b.close()


class TestFailureModes:
    def test_store_restart_mid_watch_resyncs(self):
        """The durable half (VersionedStore) survives; a new StoreServer
        over it comes back on the same port and the client's watch loop
        reconnects and resyncs — including writes it missed."""
        store = VersionedStore()
        srv = StoreServer(store=store).start_background()
        host, port = srv.address
        a = RemoteKubeStore(host, port, identity="a")
        b = RemoteKubeStore(host, port, identity="b")
        try:
            a.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
            assert b.wait_synced()
            srv.stop()
            # server-side write while b's watch is down (a new client on
            # the restarted server): b must pick it up after resync
            srv = StoreServer(host, port, store=store).start_background()
            c = RemoteKubeStore(host, port, identity="c")
            c.put_pod(Pod(name="p2", requests=Resources(cpu=1)))
            c.close()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if "default/p2" in b.pods:
                    break
                time.sleep(0.02)
            assert "default/p2" in b.pods, "watch did not resync"
            assert "default/p1" in b.pods
        finally:
            a.close()
            b.close()
            srv.stop()

    def test_request_timeout_is_retryable_error_not_hang(self):
        """A server that accepts but never answers must surface a
        StoreUnavailableError within ~the request timeout."""
        sink = socket.socket()
        sink.bind(("127.0.0.1", 0))
        sink.listen(4)
        host, port = sink.getsockname()
        held = []
        stop = threading.Event()

        def hold():
            sink.settimeout(0.2)
            while not stop.is_set():
                try:
                    conn, _ = sink.accept()
                    held.append(conn)  # read nothing, answer nothing
                except socket.timeout:
                    continue

        t = threading.Thread(target=hold, daemon=True)
        t.start()
        kube = RemoteKubeStore(
            host, port, identity="t", request_timeout=0.3, start_watch=False
        )
        try:
            t0 = time.monotonic()
            with pytest.raises(StoreUnavailableError, match="timed out"):
                kube.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
            assert time.monotonic() - t0 < 5.0, "timeout did not bound the call"
            # and the elector surface degrades to abdication, not a hang
            t0 = time.monotonic()
            assert kube.try_acquire_lease("el", "t", 1.0, 15.0) is False
            assert time.monotonic() - t0 < 5.0
        finally:
            stop.set()
            kube.close()
            for c in held:
                c.close()
            sink.close()

    def test_dead_port_retries_then_raises_retryable(self):
        free = socket.socket()
        free.bind(("127.0.0.1", 0))
        host, port = free.getsockname()
        free.close()  # nothing listens here
        kube = RemoteKubeStore(
            host, port, identity="t", connect_timeout=0.2, start_watch=False
        )
        with pytest.raises(StoreUnavailableError):
            kube.put_pod(Pod(name="p1", requests=Resources(cpu=1)))
        kube.close()

    def test_concurrent_lease_renewal_conflict_loses_cleanly(self, server):
        """resourceVersion CAS on the Lease: a renewal based on a stale
        rv returns False (conflict) — no exception, no clobber — and a
        refreshed renewal succeeds."""
        r1 = server.dispatch(
            {
                "method": "lease_acquire",
                "name": "el",
                "holder": "a",
                "now": 100.0,
                "duration_s": 15.0,
            }
        )
        assert r1["acquired"] and r1["rv"] > 0
        # the tick's acquire bumped the rv; a renewal still carrying the
        # pre-acquire rv (the background thread racing the tick) loses
        stale = server.dispatch(
            {
                "method": "lease_renew",
                "name": "el",
                "holder": "a",
                "now": 101.0,
                "base_rv": r1["rv"] - 1,
            }
        )
        assert stale["renewed"] is False and stale.get("conflict") is True
        fresh = server.dispatch(
            {
                "method": "lease_renew",
                "name": "el",
                "holder": "a",
                "now": 101.0,
                "base_rv": r1["rv"],
            }
        )
        assert fresh["renewed"] is True
        # and through the client surface: the loser returns False cleanly
        a = _client(server, identity="a2")
        b = _client(server, identity="b2")
        try:
            assert not a.try_acquire_lease("el", "a2", 102.0, 15.0)
            assert a.renew_lease("el", "a2", 103.0) is False  # not holder
        finally:
            a.close()
            b.close()


class TestLeaseHandoffOverTheWire:
    def test_acquire_exclusion_release_expiry(self, server):
        a = _client(server, identity="a")
        b = _client(server, identity="b")
        try:
            assert a.try_acquire_lease("el", "a", 100.0, 15.0)
            assert not b.try_acquire_lease("el", "b", 105.0, 15.0)
            assert a.renew_lease("el", "a", 110.0)
            # graceful release hands over immediately
            a.release_lease("el", "a")
            assert b.try_acquire_lease("el", "b", 110.1, 15.0)
            # expiry fences a crashed holder
            assert not a.try_acquire_lease("el", "a", 115.0, 15.0)
            assert a.try_acquire_lease("el", "a", 110.1 + 15.1, 15.0)
        finally:
            a.close()
            b.close()
